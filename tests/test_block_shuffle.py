"""Columnar block shuffle (round 17): codec round-trip, vectorized
hash-route parity vs the per-record oracle, merged-pass content parity
through the dataset, the MeshShuffler on the p2p host plane, the loud
TCP fallback (the hostplane=store pattern), and the TcpShuffler socket
hygiene satellites.

Slow tier: a REAL 2-process localhost ingest ladder
(tools/ingest_probe.py workers) in parity mode.
"""

import concurrent.futures
import logging
import socket
import threading

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.data.block_shuffle import (block_record_hash,
                                              block_shuffle_dests,
                                              deserialize_block,
                                              records_to_block,
                                              serialize_block, split_block)
from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.data.shuffle import (LocalShuffleGroup, MeshShuffler,
                                        ShufflePeerUnreachable, TcpShuffler,
                                        serialize_records)
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.fleet.mesh_comm import MeshComm
from paddlebox_tpu.utils.channel import Channel


def _mk_feed(dense=False, tasks=False):
    slots = [SlotConfig("click", type="float", dim=1, is_used=False),
             SlotConfig("s0", type="uint64", max_len=3),
             SlotConfig("s1", type="uint64", max_len=2),
             SlotConfig("s2", type="uint64", max_len=2)]
    if dense:
        slots.append(SlotConfig("d0", type="float", dim=2))
    kw = {}
    if tasks:
        slots.append(SlotConfig("conv", type="uint64", max_len=1,
                                is_used=False))
        kw["task_label_slots"] = (("cvr", "conv"),)
    return DataFeedConfig(slots=tuple(slots), batch_size=16, **kw)


def _mk_records(n, seed=0, dense=False, tasks=False, with_empty=False):
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        u64 = {0: rng.randint(0, 1000, rng.randint(1, 4)).astype(np.uint64),
               1: rng.randint(0, 1000, 2).astype(np.uint64)}
        if with_empty and i % 7 == 3:
            u64 = {}          # key-less record: hash falls back to label
        f32 = {0: rng.rand(2).astype(np.float32)} if dense else {}
        extra = {"cvr": int(rng.rand() < 0.3)} if tasks else {}
        recs.append(SlotRecord(label=int(rng.rand() < 0.5),
                               uint64_slots=u64, float_slots=f32,
                               ins_id="i%d" % i, extra_labels=extra))
    return recs


def _block_sig(block):
    """Order-independent multiset of per-record signatures."""
    out = []
    for r in range(block.n_recs):
        lo, hi = block.rec_offsets[r], block.rec_offsets[r + 1]
        out.append((int(block.labels[r]),
                    tuple(zip(block.key_slot[lo:hi].tolist(),
                              block.keys[lo:hi].tolist()))))
    return sorted(out)


# ---------------------------------------------------------------- codec


@pytest.mark.parametrize("dense,tasks", [(False, False), (True, False),
                                         (True, True)])
def test_codec_roundtrip(dense, tasks):
    feed = _mk_feed(dense=dense, tasks=tasks)
    recs = _mk_records(41, seed=3, dense=dense, tasks=tasks,
                       with_empty=True)
    block = records_to_block(recs, feed)
    back = deserialize_block(serialize_block(block))
    np.testing.assert_array_equal(back.keys, block.keys)
    np.testing.assert_array_equal(back.key_slot, block.key_slot)
    np.testing.assert_array_equal(back.labels, block.labels)
    np.testing.assert_array_equal(back.rec_offsets, block.rec_offsets)
    if dense:
        np.testing.assert_array_equal(back.dense, block.dense)
    else:
        assert back.dense is None
    if tasks:
        assert set(back.task_labels) == {"cvr"}
        np.testing.assert_array_equal(back.task_labels["cvr"],
                                      block.task_labels["cvr"])
    else:
        assert back.task_labels is None


def test_codec_roundtrip_empty_block():
    block = records_to_block([], _mk_feed())
    back = deserialize_block(serialize_block(block))
    assert back.n_recs == 0 and back.n_keys == 0
    assert back.rec_offsets.shape == (1,)


def test_codec_rejects_garbage():
    with pytest.raises(ValueError, match="magic"):
        deserialize_block(b"\x00" * 64)


# -------------------------------------------------------------- routing


def test_hash_parity_vs_record_oracle():
    feed = _mk_feed()
    recs = _mk_records(97, seed=5, with_empty=True)
    block = records_to_block(recs, feed)
    oracle = np.array([r.shuffle_hash() for r in recs], np.int64)
    np.testing.assert_array_equal(block_record_hash(block), oracle)
    for world in (2, 3, 5):
        np.testing.assert_array_equal(
            block_shuffle_dests(block, world), oracle % world)


def test_split_block_conservation_and_content():
    feed = _mk_feed(dense=True)
    recs = _mk_records(80, seed=9, dense=True, with_empty=True)
    block = records_to_block(recs, feed)
    world = 3
    dests = block_shuffle_dests(block, world)
    subs = split_block(block, dests, world)
    assert sum(s.n_recs for s in subs if s is not None) == 80
    for d in range(world):
        picked = [r for r, rec in zip(dests, recs) if r == d]
        oracle = [rec for rec in recs if rec.shuffle_hash() % world == d]
        if not oracle:
            assert subs[d] is None
            continue
        assert _block_sig(subs[d]) == _block_sig(
            records_to_block(oracle, feed))
        assert len(picked) == subs[d].n_recs


def test_records_to_block_matches_native_parser(tmp_path):
    """The oracle converter reproduces the PRODUCTION parser's column
    conventions — or every parity claim built on it is hollow."""
    pytest.importorskip("ctypes")
    from paddlebox_tpu.data.native_parser import NativeMultiSlotParser
    from paddlebox_tpu.data.parser import MultiSlotParser
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=60, num_slots=3,
        vocab_per_slot=40, dense_dim=2, seed=11)
    try:
        native = NativeMultiSlotParser(feed)
    except RuntimeError:
        pytest.skip("native lib unavailable")
    nb = native.parse_file_columnar(files[0])
    recs = list(MultiSlotParser(feed).parse_file(files[0]))
    rb = records_to_block(recs, feed)
    np.testing.assert_array_equal(nb.keys, rb.keys)
    np.testing.assert_array_equal(nb.key_slot, rb.key_slot)
    np.testing.assert_array_equal(nb.labels, rb.labels)
    np.testing.assert_array_equal(nb.rec_offsets, rb.rec_offsets)
    np.testing.assert_allclose(nb.dense, rb.dense, rtol=1e-6)


# ------------------------------------------------- dataset-level parity


def _load_cluster(files, feed, shufflers, columnar_flag=True):
    if not columnar_flag:
        flags.set_flag("shuffle_block_codec", False)
    try:
        dss = [BoxDataset(feed, read_threads=2, shuffler=sh)
               for sh in shufflers]
        threads = []
        for r, ds in enumerate(dss):
            ds.set_filelist(files[r::len(shufflers)])
            th = threading.Thread(target=ds.load_into_memory)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        return dss
    finally:
        flags.set_flag("shuffle_block_codec", True)


def test_merged_pass_parity_block_vs_record_codec(tmp_path):
    """The acceptance pin: a shuffled columnar pass holds EXACTLY the
    records the record-codec oracle pass holds, per rank, record for
    record (multiset — arrival order is threaded either way)."""
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=4, lines_per_file=50, num_slots=3,
        vocab_per_slot=30, seed=7)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    world = 2
    blk = _load_cluster(files, feed,
                        LocalShuffleGroup(world, 32).members)
    rec = _load_cluster(files, feed,
                        LocalShuffleGroup(world, 32).members,
                        columnar_flag=False)
    for r in range(world):
        assert blk[r]._load_columnar and not rec[r]._load_columnar
        assert len(blk[r]) == len(rec[r])
        assert _block_sig(blk[r].block) == _block_sig(
            records_to_block(rec[r].records, feed))
        np.testing.assert_array_equal(np.sort(blk[r].all_keys()),
                                      np.sort(rec[r].all_keys()))


def test_split_batches_parity_columnar_vs_record(tmp_path):
    """Memory-tier parity through split_batches: with deterministic load
    order (1 read thread, world-1 shuffler so the routed path still
    runs), the columnar pass packs bit-identical batch leaves to the
    record pass (ins_ids/qvalue extras are documented record-only)."""
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=2, lines_per_file=40, num_slots=3,
        vocab_per_slot=30, dense_dim=2, seed=13)
    feed = type(feed)(slots=feed.slots, batch_size=16)

    def load(columnar):
        sh = LocalShuffleGroup(1, 32)[0]
        ds = BoxDataset(feed, read_threads=1, shuffler=sh,
                        columnar=columnar)
        ds.set_filelist(files)
        ds.load_into_memory()
        return ds

    a, b = load(True), load(False)
    assert a._load_columnar and not b._load_columnar
    wa = a.split_batches(num_workers=2)
    wb = b.split_batches(num_workers=2)
    for ba, bb in zip([x for w in wa for x in w],
                      [x for w in wb for x in w]):
        assert ba.n_ins == bb.n_ins
        np.testing.assert_array_equal(ba.keys, bb.keys)
        np.testing.assert_array_equal(ba.slots, bb.slots)
        np.testing.assert_array_equal(ba.segments, bb.segments)
        np.testing.assert_array_equal(ba.valid, bb.valid)
        np.testing.assert_array_equal(ba.labels, bb.labels)
        np.testing.assert_array_equal(ba.ins_valid, bb.ins_valid)
        np.testing.assert_allclose(ba.dense, bb.dense, rtol=1e-6)


def test_block_to_records_roundtrip():
    """The inverse compat converter: records → block → records keeps
    every field the block codec carries."""
    from paddlebox_tpu.data.block_shuffle import block_to_records
    feed = _mk_feed(dense=True, tasks=True)
    recs = _mk_records(23, seed=4, dense=True, tasks=True,
                       with_empty=True)
    back = block_to_records(records_to_block(recs, feed), feed)
    assert len(back) == len(recs)
    for a, b in zip(recs, back):
        assert a.label == b.label
        assert set(a.uint64_slots) == set(b.uint64_slots)
        for s in a.uint64_slots:
            np.testing.assert_array_equal(np.sort(a.uint64_slots[s]),
                                          np.sort(b.uint64_slots[s]))
        assert a.extra_labels == b.extra_labels


def test_mixed_codec_frames_convert_loudly(tmp_path):
    """A peer shuffling the OTHER frame kind into this pass (rank-local
    downgrade: archive shard, native-lib-less host, split codec flag)
    DEGRADES loudly — the stray records convert at the merge instead of
    killing the cluster pass load (round-17 review). Loudness is pinned
    via the obs log tap (the obs logger does not propagate to root, so
    the warning surfaces as the log_warning_lines stat)."""
    from paddlebox_tpu.utils.stats import stat_get
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=20, num_slots=3,
        vocab_per_slot=30, seed=3)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    # direction 1: record frames into a columnar pass
    sh = LocalShuffleGroup(1, 32)[0]
    stray = _mk_records(5)
    sh._deliver(serialize_records(stray), sh.epoch)
    ds = BoxDataset(feed, read_threads=1, shuffler=sh)
    ds.set_filelist(files)
    if not ds.columnar:
        pytest.skip("native lib unavailable")
    w0 = stat_get("log_warning_lines")
    c0 = stat_get("ingest_codec_mix_converted")
    ds.load_into_memory()
    assert len(ds) == 25                 # 20 parsed + 5 converted strays
    assert stat_get("ingest_codec_mix_converted") == c0 + 5
    assert stat_get("log_warning_lines") > w0
    # direction 2: a block frame into a record-path pass
    sh2 = LocalShuffleGroup(1, 32)[0]
    blk = records_to_block(_mk_records(7, seed=9), _mk_feed())
    sh2._deliver(serialize_block(blk), sh2.epoch)
    ds2 = BoxDataset(feed, read_threads=1, shuffler=sh2, columnar=False)
    ds2.set_filelist(files)
    ds2.load_into_memory()
    assert len(ds2) == 27 and not ds2._load_columnar
    assert stat_get("ingest_codec_mix_converted") == c0 + 12


# ------------------------------------------------------ mesh transport


@pytest.fixture
def mesh_pair():
    meshes = [MeshComm(r, 2, host="127.0.0.1") for r in range(2)]
    eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
    for m in meshes:
        m.connect(eps)
    yield meshes
    for m in meshes:
        m.close()


def test_mesh_shuffler_routes_blocks(mesh_pair, tmp_path):
    from paddlebox_tpu.utils.stats import stat_get
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=4, lines_per_file=50, num_slots=3,
        vocab_per_slot=40, seed=7)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    shs = [MeshShuffler(m) for m in mesh_pair]
    try:
        b0 = stat_get("shuffle_bytes_sent")
        for pass_i in range(2):   # epoch advance over ONE shuffler set
            dss = _load_cluster(files, feed, shs)
            assert sum(len(d) for d in dss) == 200
            for r, ds in enumerate(dss):
                assert ds._load_columnar
                np.testing.assert_array_equal(
                    block_shuffle_dests(ds.block, 2),
                    np.full(len(ds), r, np.int64))
        assert stat_get("shuffle_bytes_sent") > b0
    finally:
        for sh in shs:
            sh.close()


def test_mesh_frames_before_handler_are_parked(mesh_pair):
    """A peer's readers may scatter before this rank's dataset built
    its MeshShuffler — early frames park on the mesh and drain through
    the handler at registration."""
    m0, m1 = mesh_pair
    feed = _mk_feed()
    block = records_to_block(_mk_records(9, seed=2), feed)
    payload = serialize_block(block)
    sh0 = MeshShuffler(m0)
    try:
        sh0._send(1, payload)          # rank 1 has NO shuffler yet
        # one shuffle handler per mesh: a second registration raises
        with pytest.raises(RuntimeError, match="already has"):
            MeshShuffler(m0)
        sh1 = MeshShuffler(m1)         # registration drains the parked frame
        try:
            ch = Channel()
            sh1._drain_inbox(ch)
            got = ch.drain()
            assert len(got) == 1 and got[0].n_recs == 9
        finally:
            sh1.close()
    finally:
        sh0.close()


def _fleet_pair(monkeypatch):
    """A fresh 2-rank fleet on its OWN KVStoreServer under a UNIQUE
    run id — fleets restart their collective sequence counters at 0, so
    two fleet generations sharing one store+run_id would collide on the
    same barrier/coll keys and desynchronize (the review-found flake)."""
    import uuid

    from paddlebox_tpu.fleet.fleet import Fleet
    from paddlebox_tpu.fleet.role_maker import RoleMaker
    from paddlebox_tpu.fleet.store import KVStoreServer
    monkeypatch.setenv("PBTPU_RUN_ID", uuid.uuid4().hex[:8])
    server = KVStoreServer(host="127.0.0.1")
    ep = "127.0.0.1:%d" % server.port
    fls = [Fleet().init(RoleMaker(rank=r, world=2, store_endpoint=ep))
           for r in range(2)]
    return server, fls


def test_make_shuffler_prefers_mesh(monkeypatch):
    """Fleet.make_shuffler under hostplane=p2p puts the shuffle on the
    persistent mesh."""
    server, fls = _fleet_pair(monkeypatch)
    shs = []
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        try:
            f1 = pool.submit(fls[1].make_shuffler)
            s0 = fls[0].make_shuffler()
            s1 = f1.result()
            shs += [s0, s1]
            assert isinstance(s0, MeshShuffler)
            assert isinstance(s1, MeshShuffler)
        finally:
            for s in shs:
                s.close()
            for fl in fls:
                fl.stop()
            server.stop()


def test_make_shuffler_loud_tcp_fallback(monkeypatch, caplog):
    """When mesh bring-up fails COLLECTIVELY, every rank falls back to
    the ad-hoc TcpShuffler together and warns loudly — the
    hostplane=store pattern."""
    from paddlebox_tpu.fleet import mesh_comm as mc
    server, fls = _fleet_pair(monkeypatch)
    orig = mc.MeshComm.connect

    def broken(self, endpoints, timeout=60.0):
        if self.rank == 1:
            raise mc.MeshConnectError("simulated unreachable peer")
        return orig(self, endpoints, timeout)

    shs = []
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        try:
            monkeypatch.setattr(mc.MeshComm, "connect", broken)
            with caplog.at_level(logging.WARNING, logger="paddlebox_tpu"):
                f1 = pool.submit(fls[1].make_shuffler)
                s0 = fls[0].make_shuffler()
                s1 = f1.result()
            shs += [s0, s1]
            assert isinstance(s0, TcpShuffler)
            assert isinstance(s1, TcpShuffler)
            assert any("ad-hoc TCP shuffle transport" in m
                       for m in caplog.messages)
        finally:
            for s in shs:
                s.close()
            for fl in fls:
                fl.stop()
            server.stop()


# ------------------------------------------------------ socket hygiene


def test_tcp_shuffler_named_error_on_dead_peer():
    # a bound-but-never-dialed port, released before use: dialing it
    # fails fast (refused) — the wrapper must surface the NAMED error
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    sh = TcpShuffler(0, 2, [("127.0.0.1", 0), ("127.0.0.1", dead_port)])
    old = flags.get_flag("shuffle_connect_secs")
    flags.set_flag("shuffle_connect_secs", 1.0)
    try:
        with pytest.raises(ShufflePeerUnreachable, match="peer 1"):
            sh._send(1, b"x")
    finally:
        flags.set_flag("shuffle_connect_secs", old)
        sh.close()


def test_tcp_shuffler_sets_nodelay():
    eps = [("127.0.0.1", 0), ("127.0.0.1", 0)]
    shs = []
    for r in range(2):
        sh = TcpShuffler(r, 2, eps)
        eps[r] = ("127.0.0.1", sh.port)
        shs.append(sh)
    for sh in shs:
        sh.endpoints = eps
    try:
        shs[0]._send_done(1)
        conn = shs[0]._conns[1]
        assert conn.getsockopt(socket.IPPROTO_TCP,
                               socket.TCP_NODELAY) == 1
    finally:
        for sh in shs:
            sh.close()


# ----------------------------------------------------------- slow tier


@pytest.mark.slow
def test_ingest_probe_two_ranks_parity():
    """REAL 2-process cluster: the full ingest ladder in parity mode
    (record-TCP vs block-TCP vs block-mesh land identical per-rank
    content) — the tools/ingest_probe.py workers end to end."""
    import tools.ingest_probe as ip
    r = ip.run_world(2, lines=300, files_per_rank=2, runs=1,
                     parity_only=True)
    assert r["tiers"] == {"parity": "ok"}
