"""Serving plane (round 12): mmap view stack vs the XboxModelReader
oracle, hot-key cache accounting, delta swap under load, the
plain-container serving codec, and the replica fleet."""

import os
import pickle
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.serving import (HotKeyCache, MmapViewStack,
                                   ServingClient, ServingServer,
                                   build_stack, make_manager)
from paddlebox_tpu.serving.refresh import DeltaRefreshWatcher
from paddlebox_tpu.serving.store import (compile_view_dir,
                                         discover_xbox_sources)
from paddlebox_tpu.train.checkpoint import XboxModelReader
from paddlebox_tpu.utils.stats import stat_get

D = 4


def write_view(root, day, sub=None, keys=(), rows=None, ts=None, seed=0):
    """One xbox view dir (embedding.pkl + DONE) the way the checkpoint
    writer lays them out; rows default to a seeded random matrix."""
    p = os.path.join(root, day) if sub is None else os.path.join(
        root, day, sub)
    os.makedirs(p, exist_ok=True)
    keys = np.asarray(sorted(set(int(k) for k in keys)), np.uint64)
    if rows is None:
        rows = np.random.RandomState(seed).randn(
            keys.size, D).astype(np.float32)
    with open(os.path.join(p, "embedding.pkl"), "wb") as f:
        pickle.dump({"keys": keys,
                     "embedding": np.asarray(rows, np.float32)}, f)
    with open(os.path.join(p, "DONE"), "w") as f:
        f.write(str(time.time() if ts is None else ts))
    return p


def probe_keys(rng, *key_sets, extra_misses=8):
    """Mixed probe: every key that exists somewhere + guaranteed misses,
    shuffled with duplicates."""
    pool = sorted(set().union(*[set(int(k) for k in ks)
                                for ks in key_sets]))
    misses = [max(pool, default=0) + 1 + i for i in range(extra_misses)]
    probe = np.array(pool + misses + pool[: len(pool) // 2], np.uint64)
    rng.shuffle(probe)
    return probe


# ------------------------------------------------------------------ stack


def test_stack_matches_reader_bit_parity(tmp_path):
    """Base + 2 same-day deltas + a next-day streaming delta (the
    mid-day scenario): the mmap precedence stack serves BIT-identical
    vectors to the RAM-composed XboxModelReader oracle, misses
    included."""
    root = str(tmp_path)
    rng = np.random.RandomState(0)
    k_base = rng.choice(1 << 20, 300, replace=False)
    k_d1 = rng.choice(k_base, 40, replace=False)       # overlap base
    k_d2 = np.concatenate([rng.choice(k_d1, 10, replace=False),
                           [1 << 21]])                 # overlap d1 + new
    k_next = np.concatenate([rng.choice(k_base, 25, replace=False),
                             [1 << 22]])
    write_view(root, "day0", "delta-1", k_d1, seed=1)
    write_view(root, "day0", "delta-2", k_d2, seed=2)
    write_view(root, "day0", None, k_base, seed=3)
    write_view(root, "day1", "delta-1", k_next, seed=4)

    oracle = XboxModelReader(root, "day0", "day1")
    stack, sources = build_stack(root, ["day0", "day1"])
    assert len(sources) == 4
    probe = probe_keys(rng, k_base, k_d1, k_d2, k_next)
    got = stack.lookup(probe)
    want = oracle.lookup(probe)
    np.testing.assert_array_equal(got.view(np.uint32),
                                  want.view(np.uint32))
    stack.close()


def test_stack_clock_skew_tie_break(tmp_path):
    """DONE timestamps deliberately INVERTED against structural order
    (the day-1 delta writer's clock lags the day-0 base writer's):
    precedence must follow structure, identically in oracle and
    stack — the day-1 delta still wins for its keys."""
    root = str(tmp_path)
    rng = np.random.RandomState(5)
    keys = np.arange(1, 64, dtype=np.uint64)
    # base stamped FAR in the future, deltas stamped in the past, and
    # same-day delta ids shuffled against their timestamps
    write_view(root, "day0", None, keys, seed=6, ts=4e9)
    write_view(root, "day0", "delta-1", keys[:20], seed=7, ts=3e9)
    write_view(root, "day1", "delta-1", keys[10:30], seed=8, ts=10.0)
    write_view(root, "day1", "delta-2", keys[25:40], seed=9, ts=5.0)

    sources = discover_xbox_sources(root, ["day0", "day1"])
    assert [(s.day_index, s.is_base, s.delta_id) for s in sources] == [
        (0, 0, 1), (0, 1, 0), (1, 0, 1), (1, 0, 2)]
    oracle = XboxModelReader(root, "day0", "day1")
    stack = MmapViewStack(sources)
    probe = probe_keys(rng, keys)
    np.testing.assert_array_equal(stack.lookup(probe).view(np.uint32),
                                  oracle.lookup(probe).view(np.uint32))
    stack.close()


def test_compile_view_dir_idempotent_and_shared(tmp_path):
    """The columnar twin compiles once (mtime-gated) — the path N
    serving processes share — and recompiles when the pkl changes."""
    p = write_view(str(tmp_path), "day0", None, [3, 1, 2], seed=1)
    out1 = compile_view_dir(p)
    m1 = os.path.getmtime(out1)
    assert compile_view_dir(p) == out1
    assert os.path.getmtime(out1) == m1
    time.sleep(0.02)
    write_view(str(tmp_path), "day0", None, [3, 1, 2, 4], seed=2)
    os.utime(os.path.join(p, "embedding.pkl"))
    compile_view_dir(p)
    from paddlebox_tpu.serving.store import MmapXboxStore
    st = MmapXboxStore(out1)
    assert len(st) == 4
    st.close()


def test_stack_with_empty_delta_view(tmp_path):
    """A SaveDelta where nothing crossed the threshold writes a
    ZERO-KEY view — routine right after a base save cleared delta
    scores. It must compile, open, and compose identically to the
    oracle (this crashed server bring-up and wedged the watcher before
    the round-12 file-padding fix)."""
    root = str(tmp_path)
    keys = np.arange(1, 40, dtype=np.uint64)
    write_view(root, "day0", None, keys, seed=20)
    write_view(root, "day0", "delta-1", [],
               rows=np.empty((0, D), np.float32))
    oracle = XboxModelReader(root, "day0")
    stack, sources = build_stack(root, ["day0"])
    assert len(sources) == 2
    probe = probe_keys(np.random.RandomState(21), keys)
    np.testing.assert_array_equal(stack.lookup(probe).view(np.uint32),
                                  oracle.lookup(probe).view(np.uint32))
    stack.close()


# ------------------------------------------------------------------ cache


def test_cache_admission_eviction_accounting():
    """Frequency-gated admission, CLOCK eviction, exact hit/miss/evict
    counters."""
    for name in ("serving_cache_hit", "serving_cache_miss",
                 "serving_cache_evict", "serving_cache_admit"):
        from paddlebox_tpu.utils.stats import stat_reset
        stat_reset(name)
    cache = HotKeyCache(capacity=4, dim=2, admit=2)
    rows_of = lambda ks: np.tile(  # noqa: E731
        np.asarray(ks, np.float32)[:, None], (1, 2))
    k = np.array([1, 2, 3], np.uint64)
    out = np.zeros((3, 2), np.float32)
    miss = cache.get_many(k, out)
    assert miss.all() and stat_get("serving_cache_miss") == 3
    # first offer: below the admit=2 threshold — nothing enters
    assert cache.admit_many(k, rows_of(k), epoch=0) == 0
    assert len(cache) == 0
    # second miss reaches the threshold — all 3 admitted
    assert cache.admit_many(k, rows_of(k), epoch=0) == 3
    assert len(cache) == 3
    miss = cache.get_many(k, out)
    assert not miss.any()
    np.testing.assert_array_equal(out, rows_of(k))
    assert stat_get("serving_cache_hit") == 3
    # fill to capacity, then one more hot key evicts via CLOCK; keys
    # 1..3 were just HIT (ref bits set) so the victim is the unref'd 4
    k4 = np.array([4], np.uint64)
    cache.admit_many(k4, rows_of(k4), epoch=0)
    cache.admit_many(k4, rows_of(k4), epoch=0)
    assert len(cache) == 4
    k5 = np.array([5], np.uint64)
    cache.admit_many(k5, rows_of(k5), epoch=0)
    cache.admit_many(k5, rows_of(k5), epoch=0)
    assert len(cache) == 4 and stat_get("serving_cache_evict") == 1
    out1 = np.zeros((1, 2), np.float32)
    assert not cache.get_many(np.array([5], np.uint64), out1).any()
    assert cache.get_many(np.array([4], np.uint64), out1).all()


def test_cache_stale_epoch_insert_refused():
    """An admission offer carrying a pre-swap generation must drop —
    the race guard for lookups that straddle a view swap."""
    cache = HotKeyCache(capacity=4, dim=2, admit=1)
    k = np.array([7], np.uint64)
    r = np.ones((1, 2), np.float32)
    assert cache.admit_many(k, r, epoch=0) == 1
    new_epoch = cache.clear()
    assert new_epoch == 1 and len(cache) == 0
    assert cache.admit_many(k, r, epoch=0) == 0      # stale gen: refused
    assert cache.admit_many(k, r, epoch=1) == 1


def test_cache_stale_epoch_probe_reports_all_miss():
    """The probe side of the swap guard: a get_many carrying a
    pre-swap epoch must report ALL-miss even for cached keys —
    otherwise one response could mix new-generation cache hits with
    old-grabbed-stack reads (two model generations in one pull)."""
    cache = HotKeyCache(capacity=4, dim=2, admit=1)
    k = np.array([7], np.uint64)
    r = np.full((1, 2), 5.0, np.float32)
    cache.admit_many(k, r, epoch=0)
    out = np.zeros((1, 2), np.float32)
    assert not cache.get_many(k, out, epoch=0).any()    # live epoch: hit
    cache.clear()
    cache.admit_many(k, r, epoch=1)
    out[:] = 0
    assert cache.get_many(k, out, epoch=0).all()        # stale: all-miss
    assert (out == 0).all()
    assert not cache.get_many(k, out, epoch=1).any()


def test_manager_lookup_caches_and_swap_invalidates(tmp_path):
    root = str(tmp_path)
    write_view(root, "day0", None, [1, 2, 3],
               rows=np.ones((3, D), np.float32))
    mgr, sources = make_manager(root, ["day0"], cache_rows=8,
                                cache_admit=1)
    k = np.array([1, 2], np.uint64)
    out1, gen1 = mgr.lookup(k)        # misses, admits
    out2, _ = mgr.lookup(k)           # hits
    np.testing.assert_array_equal(out1, out2)
    assert len(mgr.cache) == 2
    # swap: key 2 changes; the cache must not serve the old vector
    write_view(root, "day1", "delta-1", [2],
               rows=np.full((1, D), 9, np.float32))
    w = DeltaRefreshWatcher(mgr, root, poll_secs=10.0,
                            known_sources=sources)
    assert w.poll_once()
    out3, gen3 = mgr.lookup(k)
    assert gen3 == gen1 + 1
    np.testing.assert_array_equal(out3[1], np.full(D, 9, np.float32))
    mgr.close()


def test_manager_tracks_cache_epoch_not_gen(tmp_path):
    """The stale-admission guard must track the cache's OWN epoch, not
    assume epoch == manager generation: a cache that was cleared before
    the manager existed (epoch ahead of gen 0) must still admit."""
    from paddlebox_tpu.serving.refresh import ViewManager
    root = str(tmp_path)
    write_view(root, "day0", None, [1, 2],
               rows=np.ones((2, D), np.float32))
    stack, _ = build_stack(root, ["day0"])
    cache = HotKeyCache(capacity=4, dim=D, admit=1)
    cache.clear()
    cache.clear()                      # epoch now 2, gen will start 0
    mgr = ViewManager(stack, cache)
    mgr.lookup(np.array([1], np.uint64))
    assert len(cache) == 1, "admission must survive epoch != gen"
    mgr.close()


# ---------------------------------------------------------------- refresh


def test_swap_under_load_no_drops(lock_order_watch, tmp_path):
    """Reader threads hammer lookups while deltas land and swap: no
    request may error or read a torn view (vectors are always exactly
    one of the generations' values), and the new vector must be served
    within one poll interval."""
    root = str(tmp_path)
    keys = np.arange(1, 33, dtype=np.uint64)
    write_view(root, "day0", None, keys,
               rows=np.zeros((32, D), np.float32))
    mgr, sources = make_manager(root, ["day0"], cache_rows=16,
                                cache_admit=1)
    watcher = DeltaRefreshWatcher(mgr, root, poll_secs=0.05,
                                  known_sources=sources).start()
    errors = []
    stop = threading.Event()
    seen_vals = set()

    def hammer():
        rng = np.random.RandomState(os.getpid())
        while not stop.is_set():
            try:
                out, _gen = mgr.lookup(keys)
                vals = set(np.unique(out).tolist())
                if not vals <= {0.0, 1.0, 2.0, 3.0}:
                    errors.append(f"torn read: {sorted(vals)[:4]}")
                seen_vals.update(vals)
            except Exception as e:   # NO dropped/errored lookups allowed
                errors.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i, v in enumerate((1.0, 2.0, 3.0), 1):
            write_view(root, "day1", f"delta-{i}", keys,
                       rows=np.full((32, D), v, np.float32))
            deadline = time.time() + 5.0
            while time.time() < deadline:
                out, _ = mgr.lookup(keys[:1])
                # the poll loop's reads are served lookups too — on a
                # 1-core box the hammer threads may get no timeslice
                # between the LAST swap and stop, so the generation
                # coverage assertion must count these observations
                seen_vals.add(float(out[0, 0]))
                if out[0, 0] == v:
                    break
                time.sleep(0.01)
            else:
                errors.append(f"delta {i} not served within 5s")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        watcher.stop()
        mgr.close()
    assert not errors, errors[:5]
    assert {1.0, 2.0, 3.0} <= seen_vals


# ------------------------------------------------------------ rpc + fleet


@pytest.fixture
def tiny_server(tmp_path):
    root = str(tmp_path)
    rng = np.random.RandomState(11)
    keys = rng.choice(1 << 16, 200, replace=False)
    write_view(root, "day0", None, keys, seed=12)
    flags.set_flag("serving_report_requests", 4)
    server = ServingServer(root, days=["day0"], watch=False)
    client = ServingClient([("127.0.0.1", server.port)])
    yield root, keys, server, client
    client.close()
    server.drain(timeout=5.0)


def test_server_pull_parity_and_obs(tiny_server):
    """RPC-served vectors are bit-identical to the oracle; the obs
    plane publishes latency percentiles + cache hit rate."""
    root, keys, server, client = tiny_server
    rng = np.random.RandomState(13)
    oracle = XboxModelReader(root, "day0")
    probe = probe_keys(rng, keys)
    for _ in range(6):                 # cross the report cadence
        got = client.pull(probe)
    np.testing.assert_array_equal(
        got.view(np.uint32), oracle.lookup(probe).view(np.uint32))
    rep = server.reporter.peek()
    assert rep is not None and rep["role"] == "serving"
    assert "serving_lookup_us" in rep["hists"]
    assert rep["hists"]["serving_lookup_us"]["p99"] > 0
    assert rep["cache_hit_rate"] is not None
    st = client.stats()
    assert st["requests"] >= 6 and st["gen"] == 0


def test_serving_slo_burn_gauge_and_rpc_trace(tiny_server):
    """Round 14: (a) every report window carries gauge serving_slo_burn
    = window p99 / serving_slo_us (the health plane's SLO signal); (b)
    one pull's trace id lands on BOTH the client-side and server-side
    spans — the correlation trace_stitch draws across the RPC boundary
    (client and replica share this process's tracer here, so the pair
    is directly observable)."""
    from paddlebox_tpu.obs.tracer import get_tracer
    root, keys, server, client = tiny_server
    get_tracer().clear()
    rng = np.random.RandomState(17)
    probe = probe_keys(rng, keys)
    for _ in range(5):                 # cross the cadence (4 requests)
        client.pull(probe)
    rep = server.reporter.peek()
    assert rep is not None
    burn = rep["gauges"].get("serving_slo_burn")
    assert burn is not None and burn > 0
    slo = float(flags.get_flag("serving_slo_us"))
    assert burn == pytest.approx(
        rep["hists"]["serving_lookup_us"]["p99"] / slo, rel=0.05)
    spans = get_tracer().all_spans()
    client_t = {s[5] for s in spans if s[0] == "serving_pull_client"}
    server_t = {s[5] for s in spans if s[0] == "serving_pull"}
    shared = (client_t & server_t) - {None}
    assert shared, (client_t, server_t)
    assert all(t >> 63 for t in shared)    # request-id space, 64-bit


def test_serving_codec_rejects_class_payloads(tiny_server):
    """A pickled numpy array (class resolution) on the serving port is
    refused by the transport, the stream stays in sync, and a plain
    pull on the SAME connection still works."""
    from paddlebox_tpu.utils.rpc import FramedClient
    _root, keys, server, _client = tiny_server
    raw = FramedClient("127.0.0.1", server.port)  # default plain loads
    try:
        # hand-roll a class-bearing request: FramedClient pickles
        # whatever we pass — a numpy array needs find_class to load
        with pytest.raises(RuntimeError, match="refusing to unpickle"):
            raw.call({"method": "pull",
                      "keys": np.asarray(keys[:3], np.uint64), "n": 3})
        from paddlebox_tpu.serving import codec
        resp = raw.call(codec.encode_pull(np.asarray(keys[:3],
                                                     np.uint64)))
        assert codec.decode_rows(resp).shape == (3, D)
        # malformed plain frames fail loud, stream still alive
        with pytest.raises(RuntimeError, match="length mismatch"):
            raw.call({"method": "pull", "keys": b"xx", "n": 3})
        assert raw.call({"method": "ping"})["gen"] == 0
    finally:
        raw.close()


def test_server_drain_refuses_then_stops(tmp_path):
    root = str(tmp_path)
    write_view(root, "day0", None, [1, 2], seed=14)
    server = ServingServer(root, days=["day0"], watch=False)
    client = ServingClient([("127.0.0.1", server.port)])
    client.pull(np.array([1], np.uint64))
    assert server.drain(timeout=5.0)
    with pytest.raises((ConnectionError, RuntimeError)):
        client.pull(np.array([1], np.uint64))
    client.close()


@pytest.mark.slow
def test_fleet_two_process_smoke(tmp_path):
    """2 spawned replicas over one store root: parity pulls through
    round-robin + failover, per-replica stats, graceful close."""
    from paddlebox_tpu.serving import ServingFleet
    root = str(tmp_path)
    rng = np.random.RandomState(15)
    keys = rng.choice(1 << 18, 500, replace=False)
    write_view(root, "day0", None, keys, seed=16)
    oracle = XboxModelReader(root, "day0")
    probe = probe_keys(rng, keys)
    with ServingFleet(root, days=["day0"], processes=2) as fleet:
        assert len(fleet.endpoints) == 2
        client = fleet.client()
        for _ in range(4):             # round-robin hits both replicas
            got = client.pull(probe)
        np.testing.assert_array_equal(
            got.view(np.uint32), oracle.lookup(probe).view(np.uint32))
        assert client.stats(0)["requests"] + client.stats(1)[
            "requests"] == 4
        client.close()
