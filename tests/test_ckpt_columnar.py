"""Round 15: the line-rate checkpoint/restore plane.

Columnar sharded sparse checkpoints (manifest + striped parts, writer/
reader pools) vs the pickle oracle — bit-parity, crash-mid-save
atomicity, spilled rows, legacy back-compat; the touched-row journal —
replay-over-base bit-exactness against the live store, touched
save == full save, taint/rotation/fallback honesty; the CheckpointManager
writer tracking; and the serving side's detect-and-skip on directly-
emitted columnar views."""

import json
import os
import pickle
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (CheckpointConfig,
                                          SparseOptimizerConfig,
                                          TableConfig)
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding import ckpt_store as cks
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.embedding.host_store import HostEmbeddingStore
from paddlebox_tpu.embedding.pass_table import PassTable
from paddlebox_tpu.train import journal as jr
from paddlebox_tpu.train.checkpoint import (SPARSE_MANIFEST, SPARSE_PICKLE,
                                            CheckpointManager)

D = 4
CAP = 1 << 10


def table_cfg(**kw):
    return TableConfig(
        embedx_dim=D, pass_capacity=CAP,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3), **kw)


def fill_store(store, n=1000, seed=0):
    rng = np.random.RandomState(seed)
    keys = np.unique(rng.randint(1, 1 << 40, n).astype(np.uint64))
    vals = rng.rand(keys.size, store.layout.width).astype(np.float32)
    vals[:, acc.SHOW] = rng.randint(1, 50, keys.size)
    vals[:, acc.CLICK] = rng.randint(0, 5, keys.size)
    vals[:, acc.UNSEEN_DAYS] = 0.0
    store.assign(keys, vals)
    return keys, vals


def sorted_items(store):
    keys, vals = store.state_items()
    order = np.argsort(keys)
    return keys[order], vals[order]


def drive_pass(table, keys, grad_scale=0.05):
    """One real train pass over `keys` (dedup + push + touched
    writeback)."""
    table.begin_feed_pass()
    table.add_keys(keys)
    table.end_feed_pass()
    table.begin_pass()
    pl = table.push_layout
    sub = np.concatenate([keys[: max(1, keys.size // 2)], keys[:5]])
    ids = table.lookup_ids(sub)
    g = np.zeros((ids.size, pl.width), np.float32)
    g[:, pl.SHOW] = 1.0
    g[:, pl.CLICK] = (np.arange(ids.size) % 2).astype(np.float32)
    g[:, pl.EMBED_G] = grad_scale
    g[:, pl.embedx_g:] = 0.01
    table.push(jnp.asarray(ids), jnp.asarray(g))
    table.end_pass()


# --------------------------------------------------------------- format tier


def test_columnar_roundtrip_bit_identical_to_pickle(tmp_path):
    layout = ValueLayout(D)
    st = HostEmbeddingStore(layout, table_cfg())
    keys, _ = fill_store(st, 2000)
    meta = {"embedx_dim": D, "optimizer": layout.optimizer}
    k0, v0 = st.state_items()

    man = str(tmp_path / "sparse.xman")
    cks.write_sparse_columnar(man, k0, v0, meta, parts=5)
    blob = cks.load_sparse_any(man)
    # contiguous stripes concatenated in manifest order == the arrays a
    # pickle blob would carry, byte for byte
    np.testing.assert_array_equal(blob["keys"], k0)
    np.testing.assert_array_equal(blob["values"], v0)

    # store-level round trip parity: columnar load == pickle load
    pkl = str(tmp_path / "sparse.pkl")
    with open(pkl, "wb") as f:
        pickle.dump({"keys": k0, "values": v0, "embedx_dim": D,
                     "optimizer": layout.optimizer}, f)
    st_a = HostEmbeddingStore(layout, table_cfg())
    st_a.load(man)
    st_b = HostEmbeddingStore(layout, table_cfg())
    st_b.load(pkl)
    ka, va = sorted_items(st_a)
    kb, vb = sorted_items(st_b)
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_array_equal(va, vb)
    assert keys.size == ka.size


def test_load_blob_beyond_capacity_free_list_integrity(tmp_path):
    """Review find: loading a blob LARGER than a fresh store's capacity
    must leave the free list and index disjoint — the vectorized
    install's tail-delete freed rows that were in use, and the next
    created key silently clobbered a restored feature."""
    from paddlebox_tpu.embedding.host_store import _GROW
    layout = ValueLayout(D)
    n = _GROW + 1000  # forces _grow during the restore itself
    keys = np.arange(1, n + 1, dtype=np.uint64)
    vals = np.tile(np.arange(n, dtype=np.float32)[:, None],
                   (1, layout.width))
    st = HostEmbeddingStore(layout, table_cfg())  # FRESH — capacity _GROW
    st.load_blob({"keys": keys, "values": vals, "embedx_dim": D,
                  "optimizer": layout.optimizer})
    in_use = set(st._index.values())
    assert not in_use.intersection(st._free)
    assert len(in_use) + len(st._free) == st._values.shape[0]
    # the next created key must take a genuinely free row, clobbering
    # nothing
    st.lookup_or_create(np.uint64([n + 7]))
    got = st.lookup(keys[1000:1001])[0]
    np.testing.assert_array_equal(got, vals[1000])


def test_columnar_empty_store_roundtrip(tmp_path):
    layout = ValueLayout(D)
    st = HostEmbeddingStore(layout, table_cfg())
    man = str(tmp_path / "empty.xman")
    st.save(man)
    st2 = HostEmbeddingStore(layout, table_cfg())
    st2.load(man)
    assert len(st2) == 0


def test_manifest_pins_part_list_against_strays(tmp_path):
    """A retried save with FEWER parts must not read a stale extra part
    from the interrupted wider save."""
    layout = ValueLayout(D)
    st = HostEmbeddingStore(layout, table_cfg())
    fill_store(st, 600)
    k0, v0 = st.state_items()
    meta = {"embedx_dim": D, "optimizer": layout.optimizer}
    man = str(tmp_path / "s.xman")
    cks.write_sparse_columnar(man, k0, v0, meta, parts=6)
    cks.write_sparse_columnar(man, k0, v0, meta, parts=2)
    assert os.path.exists(man + ".p0005")  # the stray is still on disk
    blob = cks.load_sparse_columnar(man)
    np.testing.assert_array_equal(blob["keys"], k0)
    np.testing.assert_array_equal(blob["values"], v0)


def test_native_store_columnar_roundtrip(tmp_path):
    from paddlebox_tpu.embedding.native_store import NativeHostEmbeddingStore
    try:
        st = NativeHostEmbeddingStore(ValueLayout(D), table_cfg())
    except RuntimeError:
        pytest.skip("native lib unavailable")
    fill_store(st, 1500)
    k0, v0 = sorted_items(st)
    man = str(tmp_path / "n.xman")
    st.save(man)
    st2 = NativeHostEmbeddingStore(ValueLayout(D), table_cfg())
    st2.load(man)
    k1, v1 = sorted_items(st2)
    np.testing.assert_array_equal(k0, k1)
    np.testing.assert_array_equal(v0, v1)


# ----------------------------------------------------------- manager + crash


def mk_cm(tmp_path, table, async_save=False, sub="a"):
    return CheckpointManager(
        CheckpointConfig(batch_model_dir=str(tmp_path / sub / "batch"),
                         xbox_model_dir=str(tmp_path / sub / "xbox"),
                         async_save=async_save), table)


def test_crash_mid_save_previous_done_base_still_loads(tmp_path,
                                                       monkeypatch):
    t = PassTable(table_cfg(), seed=3)
    drive_pass(t, np.arange(1, 400, dtype=np.uint64) * 7)
    cm = mk_cm(tmp_path, t)
    # snapshot BEFORE save: the post-save stat mutation (delta clear +
    # aging) is the documented save_base semantics — the artifact holds
    # the pre-mutation state
    k0, v0 = sorted_items(t.store)
    cm.save_base({"w": 1.0}, {"m": 0.0}, day="d0")

    calls = {"n": 0}
    real = cks.write_part

    def dying_write_part(path, keys, values, fsync=True):
        calls["n"] += 1
        if calls["n"] >= 3:
            raise OSError("injected writer death between part files")
        return real(path, keys, values, fsync=fsync)

    monkeypatch.setattr(cks, "write_part", dying_write_part)
    drive_pass(t, np.arange(1, 500, dtype=np.uint64) * 11)
    with pytest.raises(OSError):
        cm.save_base({"w": 2.0}, {"m": 0.0}, day="d1")
    monkeypatch.setattr(cks, "write_part", real)
    # d1 never completed: no manifest, no DONE → it must not load...
    assert not os.path.exists(
        os.path.join(cm.cfg.batch_model_dir, "d1", SPARSE_MANIFEST))
    with pytest.raises(FileNotFoundError):
        cm.load_base("d1")
    # ...and the previous DONE base is intact
    params, _, _ = cm.load_base("d0")
    assert params == {"w": 1.0}
    k1, v1 = sorted_items(t.store)
    np.testing.assert_array_equal(k0, k1)
    np.testing.assert_array_equal(v0, v1)


def test_legacy_pickle_checkpoint_still_loads(tmp_path):
    t = PassTable(table_cfg(), seed=5)
    drive_pass(t, np.arange(1, 300, dtype=np.uint64) * 13)
    flags.set_flag("ckpt_format", "pickle")
    cm = mk_cm(tmp_path, t)
    k0, v0 = sorted_items(t.store)  # pre-mutation snapshot = the artifact
    cm.save_base({"p": 1}, {}, day="d0")
    assert os.path.exists(
        os.path.join(cm.cfg.batch_model_dir, "d0", SPARSE_PICKLE))
    # a columnar-era run resumes from the pickle-era checkpoint
    flags.set_flag("ckpt_format", "columnar")
    drive_pass(t, np.arange(1, 200, dtype=np.uint64) * 17)
    cm.load_base("d0")
    k1, v1 = sorted_items(t.store)
    np.testing.assert_array_equal(k0, k1)
    np.testing.assert_array_equal(v0, v1)


def test_columnar_base_covers_spilled_rows(tmp_path):
    cfg = table_cfg(ssd_dir=str(tmp_path / "ssd"), ssd_threshold_mb=1)
    layout = ValueLayout(D)
    st = HostEmbeddingStore(layout, cfg)
    keys, _ = fill_store(st, 400)
    assert st.spill(max_resident=keys.size // 2) == keys.size - keys.size // 2
    man = str(tmp_path / "sp.xman")
    st.save(man)
    st2 = HostEmbeddingStore(layout, cfg)
    st2.load(man)
    got, _ = st2.state_items()
    assert set(got.tolist()) == set(keys.tolist())


def test_writer_tracking_joins_every_outstanding_writer(tmp_path):
    """The single-slot _save_thread bug: two outstanding async writers,
    wait() must join BOTH (and a load must wait for writers)."""
    t = PassTable(table_cfg(), seed=7)
    drive_pass(t, np.arange(1, 100, dtype=np.uint64) * 3)
    cm = mk_cm(tmp_path, t, async_save=True)
    done = []
    gates = [threading.Event(), threading.Event()]
    for i in range(2):
        def writer(i=i):
            gates[i].wait(5.0)
            done.append(i)
        cm._spawn_writer(writer)
    assert len(cm._writers) == 2  # both handles tracked, none dropped
    for g in gates:
        g.set()
    cm.wait()
    assert sorted(done) == [0, 1]
    assert not cm._writers

    # end-to-end: an async base save joined by the next load
    cm.save_base({"w": 3}, {}, day="d0")
    params, _, _ = cm.load_base("d0")  # load_base wait()s internally
    assert params == {"w": 3}


# ------------------------------------------------------------------- journal


def run_cadence(tmp_path, sub, seed=21, mode="full"):
    """Passes + mid-day delta + day-boundary base saves with a live
    journal; returns (table, cm, sorted store state AFTER everything)."""
    rng = np.random.RandomState(seed)
    t = PassTable(table_cfg(), seed=seed)
    cm = mk_cm(tmp_path, t, sub=sub)
    base = np.unique(rng.randint(1, 1 << 30, 500).astype(np.uint64))
    drive_pass(t, base)
    cm.save_base({"w": 0}, {}, day="d0")        # full anchor
    # day d1: touched passes + a SaveDelta stat rewrite + day boundary
    drive_pass(t, base[: base.size // 3])
    cm.save_delta("d1", delta_id=1)
    fresh = np.unique(rng.randint(1, 1 << 30, 80).astype(np.uint64))
    drive_pass(t, np.unique(np.concatenate([base[::4], fresh])))
    cm.save_base({"w": 1}, {}, day="d1", mode=mode)
    t.end_day(age=False)
    return t, cm


def test_journal_replay_over_base_matches_live_store(tmp_path):
    """The elastic-rejoin contract: full base + journal segments replay
    == the live store, bit-exact — through real passes, a save_delta
    stat rewrite, a day-boundary save's stat mutation and end_day."""
    t, cm = run_cadence(tmp_path, "jr", mode="full")
    drive_pass(t, np.arange(1, 300, dtype=np.uint64) * 19)  # mid-day d2
    assert cm.journal is not None and cm.journal.snapshot_ready()
    refs = cm.journal.snapshot_refs()
    base_blob = cm._read_base_files(refs["parts"])
    rebuilt = jr.reconstruct_blob(base_blob, refs["segments"],
                                  t.layout, t.config)
    ko, vo = sorted_items(t.store)
    order = np.argsort(rebuilt["keys"])
    np.testing.assert_array_equal(rebuilt["keys"][order], ko)
    np.testing.assert_array_equal(rebuilt["values"][order], vo)


def test_touched_save_restores_identically_to_full_save(tmp_path):
    """save_base(mode='touched') → load_base must reconstruct the exact
    store a full save at the same instant would have restored."""
    t1, cm1 = run_cadence(tmp_path, "full", seed=33, mode="full")
    t2, cm2 = run_cadence(tmp_path, "touched", seed=33, mode="touched")
    # the touched artifact is journal-mode on disk
    man = json.load(open(os.path.join(cm2.cfg.batch_model_dir, "d1",
                                      SPARSE_MANIFEST)))
    assert man["mode"] == "journal" and man["segments"]
    cm1.load_base("d1")
    cm2.load_base("d1")
    k1, v1 = sorted_items(t1.store)
    k2, v2 = sorted_items(t2.store)
    np.testing.assert_array_equal(k1, k2)
    np.testing.assert_array_equal(v1, v2)
    # and the journal keeps working after a restore: next touched save
    drive_pass(t2, np.arange(1, 150, dtype=np.uint64) * 23)
    bdir, xdir = cm2.save_base({"w": 9}, {}, day="d2", mode="auto")
    assert xdir is None  # touched saves carry no xbox base
    assert json.load(open(os.path.join(
        bdir, SPARSE_MANIFEST)))["mode"] == "journal"


def test_touched_mode_without_journal_falls_back_full(tmp_path):
    """ckpt_journal off (or journal dir uncreatable): an explicit
    mode='touched' save degrades to a loud FULL save, never a crash."""
    flags.set_flag("ckpt_journal", False)
    t = PassTable(table_cfg(), seed=19)
    drive_pass(t, np.arange(1, 150, dtype=np.uint64) * 47)
    cm = mk_cm(tmp_path, t)
    assert cm.journal is None
    bdir, xdir = cm.save_base({}, {}, day="d0", mode="touched")
    assert json.load(open(os.path.join(
        bdir, SPARSE_MANIFEST)))["mode"] == "full"
    assert xdir is not None
    cm.load_base("d0")


def test_retry_after_writer_death_sweeps_orphan_tmps(tmp_path,
                                                     monkeypatch):
    """A writer that dies between open() and rename leaves a pid/tid
    tmp a retry would never overwrite — the retry sweeps it."""
    layout = ValueLayout(D)
    st = HostEmbeddingStore(layout, table_cfg())
    fill_store(st, 300)
    k0, v0 = st.state_items()
    meta = {"embedx_dim": D, "optimizer": layout.optimizer}
    man = str(tmp_path / "s.xman")
    # fake a dead writer's orphan
    orphan = f"{man}.p0000.12345.67890.tmp"
    cks.write_sparse_columnar(man, k0, v0, meta, parts=2)
    with open(orphan, "wb") as f:
        f.write(b"garbage")
    cks.write_sparse_columnar(man, k0, v0, meta, parts=2)
    assert not os.path.exists(orphan)


def test_touched_mode_without_anchor_falls_back_full(tmp_path):
    t = PassTable(table_cfg(), seed=9)
    drive_pass(t, np.arange(1, 200, dtype=np.uint64) * 29)
    cm = mk_cm(tmp_path, t)
    bdir, xdir = cm.save_base({}, {}, day="d0", mode="auto")
    # no prior full base → auto resolves to FULL (and emits the xbox base)
    assert json.load(open(os.path.join(
        bdir, SPARSE_MANIFEST)))["mode"] == "full"
    assert xdir is not None


def test_spill_is_journaled_touched_save_stays_exact(tmp_path):
    """Round 16: spill is a journaled MOVE, not a taint — the epoch
    stays snapshot-ready and the touched save reconstructs the live
    store (resident + tier) bit-exactly."""
    t = PassTable(table_cfg(), seed=13)
    drive_pass(t, np.arange(1, 300, dtype=np.uint64) * 31)
    cm = mk_cm(tmp_path, t)
    cm.save_base({}, {}, day="d0")
    assert cm.journal.snapshot_ready()
    t.store._spill_dir = str(tmp_path / "ssd")  # arm the spill tier
    with t.store_lock:
        assert t.store.spill(max_resident=50) > 0
    assert t.store.spilled_count() > 0
    # a spill no longer taints: the MOVE record keeps the epoch exact
    assert cm.journal.snapshot_ready()
    drive_pass(t, np.arange(1, 300, dtype=np.uint64) * 31)  # faults some back
    # live state (resident + tier at EFFECTIVE values) BEFORE the save:
    # the touched artifact anchors on the pre-mutation snapshot
    lk, lv = t.store.state_items()
    sk, sv = t.store.spilled_snapshot()
    if sk.size:
        lk, lv = np.concatenate([lk, sk]), np.vstack([lv, sv])
    lo = np.argsort(lk, kind="stable")
    bdir, _ = cm.save_base({}, {}, day="d1", mode="auto")
    assert json.load(open(os.path.join(
        bdir, SPARSE_MANIFEST)))["mode"] == "journal"
    t2 = PassTable(table_cfg(), seed=99)
    cm2 = CheckpointManager(
        CheckpointConfig(batch_model_dir=str(tmp_path / "a" / "batch"),
                         xbox_model_dir=str(tmp_path / "a" / "xbox"),
                         async_save=False), t2)
    cm2.load_base("d1")
    rk, rv = t2.store.state_items()
    ro = np.argsort(rk, kind="stable")
    np.testing.assert_array_equal(rk[ro], lk[lo])
    np.testing.assert_array_equal(rv[ro], lv[lo])


def test_journal_rotation_bound_marks_incomplete(tmp_path):
    layout = ValueLayout(D)
    j = jr.TouchedRowJournal(str(tmp_path / "j"), layout, table_cfg(),
                             segment_bytes=2048, max_segments=2)
    j.anchor_full(["/nonexistent/base.p0000"])
    rng = np.random.RandomState(0)
    for _ in range(8):  # each append rotates past 2 KB quickly
        keys = rng.randint(1, 1 << 30, 64).astype(np.uint64)
        j.append_rows(keys, rng.rand(64, layout.width).astype(np.float32))
    assert not j.snapshot_ready()
    with pytest.raises(jr.JournalIncompleteError):
        j.snapshot_refs()


def test_snapshot_seal_itself_tripping_rotation_refuses(tmp_path):
    """Review find: snapshot_refs seals the ACTIVE segment, and that
    seal can trip the rotation bound — the completeness check must run
    AFTER the seal, or the snapshot silently omits the dropped rows."""
    layout = ValueLayout(D)
    j = jr.TouchedRowJournal(str(tmp_path / "j"), layout, table_cfg(),
                             segment_bytes=1 << 20, max_segments=2)
    j.anchor_full(["/nonexistent/base.p0000"])
    rng = np.random.RandomState(0)

    def rows():
        keys = rng.randint(1, 1 << 30, 64).astype(np.uint64)
        j.append_rows(keys, rng.rand(64, layout.width).astype(np.float32))

    rows()
    j._seal_locked()  # sealed #1 (test hook: force rotation points)
    rows()
    j._seal_locked()  # sealed #2 == max_segments; epoch still complete
    rows()            # active segment with live rows
    assert j.snapshot_ready()  # the pre-seal view looks complete...
    with pytest.raises(jr.JournalIncompleteError):
        j.snapshot_refs()      # ...but sealing would drop segment #1


def test_move_records_replay_tier_moves_exactly(tmp_path):
    """Round 16: MV_SPILL / MV_FAULT_IN records replay as spill_exact /
    fault_in_keys on the scratch store — a raw segment replay lands the
    same rows on the same side of the resident/tier boundary, values
    intact, with no taint anywhere in the cadence."""
    layout = ValueLayout(D)
    j = jr.TouchedRowJournal(str(tmp_path / "j"), layout, table_cfg())
    j.anchor_full(["/nonexistent/base.p0000"])
    keys = np.arange(1, 33, dtype=np.uint64)
    j.append_rows(keys, np.ones((32, layout.width), np.float32))
    j.append_move(jr.MV_SPILL, keys[:10])
    j.append_move(jr.MV_FAULT_IN, keys[:4])
    j.close()
    segs = sorted(os.path.join(str(tmp_path / "j"), p)
                  for p in os.listdir(str(tmp_path / "j"))
                  if p.endswith(".jrnl"))
    st = HostEmbeddingStore(layout, table_cfg())
    jr.replay_segments(st, table_cfg(), segs)
    assert len(st) == 26              # 32 - 10 spilled + 4 faulted back
    assert st.spilled_count() == 6
    np.testing.assert_array_equal(np.sort(st.spilled_keys()), keys[4:10])
    got = st.lookup(keys)             # peeks tier rows without moving them
    np.testing.assert_array_equal(got, np.ones((32, layout.width),
                                               np.float32))
    assert st.spilled_count() == 6


def test_restart_sweeps_stale_segments(tmp_path):
    """A restarted process's journal can't replay its predecessor's
    segments (anchor gone) — construction sweeps them instead of
    accumulating orphans across restarts."""
    layout = ValueLayout(D)
    j1 = jr.TouchedRowJournal(str(tmp_path / "j"), layout, table_cfg())
    j1.append_rows(np.arange(1, 9, dtype=np.uint64),
                   np.ones((8, layout.width), np.float32))
    j1.close()
    assert any(p.endswith(".jrnl") for p in os.listdir(str(tmp_path / "j")))
    jr.TouchedRowJournal(str(tmp_path / "j"), layout, table_cfg())
    assert not any(p.endswith((".jrnl", ".open"))
                   for p in os.listdir(str(tmp_path / "j")))


def test_touched_save_io_death_falls_back_full(tmp_path):
    """Review find: a pruned anchor part (or a dead async writer that
    never materialized it) must degrade to a LOUD full save, not crash
    the day boundary."""
    t, cm = run_cadence(tmp_path, "io", seed=55, mode="full")
    drive_pass(t, np.arange(1, 120, dtype=np.uint64) * 43)
    # sabotage the anchor: point it at part files that don't exist
    cm.journal.rebase(["/nonexistent/base.p0000"], [])
    assert cm.journal.snapshot_ready()  # refusal machinery can't see it
    bdir, xdir = cm.save_base({}, {}, day="d9", mode="touched")
    assert json.load(open(os.path.join(
        bdir, SPARSE_MANIFEST)))["mode"] == "full"
    assert xdir is not None


def test_journal_segment_survives_torn_tail(tmp_path):
    """A crash mid-append leaves a parseable prefix, not garbage."""
    layout = ValueLayout(D)
    j = jr.TouchedRowJournal(str(tmp_path / "j"), layout, table_cfg())
    keys = np.arange(1, 65, dtype=np.uint64)
    vals = np.random.RandomState(1).rand(64, layout.width).astype(np.float32)
    j.append_rows(keys, vals)
    j.append_rows(keys, vals)
    j.close()
    seg = [p for p in os.listdir(str(tmp_path / "j"))
           if p.endswith(".jrnl")][0]
    path = os.path.join(str(tmp_path / "j"), seg)
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:-17])  # tear the last record mid-payload
    recs = list(jr.iter_segment(path))
    kinds = [k for k, _ in recs]
    assert kinds == [jr.KIND_HEADER, jr.KIND_ROWS]  # torn tail dropped


def test_prune_safe_artifacts_survive_base_dir_deletion(tmp_path):
    """Touched artifacts hard-link their base: retention-pruning the
    ORIGINAL full-base dir must not break a later touched artifact."""
    import shutil
    t, cm = run_cadence(tmp_path, "pr", seed=44, mode="touched")
    # what d1's artifact must reconstruct: its own links, no d0 needed
    oracle = cm._reconstruct_journal_manifest(
        os.path.join(cm.cfg.batch_model_dir, "d1"),
        cks.read_manifest(os.path.join(cm.cfg.batch_model_dir, "d1",
                                       SPARSE_MANIFEST)))
    shutil.rmtree(os.path.join(cm.cfg.batch_model_dir, "d0"))
    drive_pass(t, np.arange(1, 100, dtype=np.uint64) * 37)
    cm.load_base("d1")  # reconstructs from d1's own links
    k1, v1 = sorted_items(t.store)
    order = np.argsort(oracle["keys"])
    np.testing.assert_array_equal(oracle["keys"][order], k1)
    np.testing.assert_array_equal(oracle["values"][order], v1)


# ----------------------------------------------------------- serving plane


def test_compile_view_dir_skips_directly_emitted_columnar(tmp_path,
                                                          monkeypatch):
    """New-format view dirs (view.xcol, no embedding.pkl): compile is a
    detect-and-skip no-op — zero bytes rewritten on every call."""
    from paddlebox_tpu.serving import store as sstore
    t = PassTable(table_cfg(), seed=15)
    drive_pass(t, np.arange(1, 300, dtype=np.uint64) * 41)
    cm = mk_cm(tmp_path, t)
    _, xbox_dir = cm.save_base({}, {}, day="d0")
    assert not os.path.exists(os.path.join(xbox_dir, "embedding.pkl"))
    out = sstore.compile_view_dir(xbox_dir)
    st0 = os.stat(out)
    writes = {"n": 0}
    real = sstore.write_xbox_columnar

    def counting(*a, **kw):
        writes["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(sstore, "write_xbox_columnar", counting)
    assert sstore.compile_view_dir(xbox_dir) == out
    st1 = os.stat(out)
    assert writes["n"] == 0  # zero bytes rewritten on the second call
    assert (st0.st_ino, st0.st_mtime_ns) == (st1.st_ino, st1.st_mtime_ns)


def test_mixed_format_views_compose(tmp_path):
    """A pkl-era base day composes with a columnar-era delta through
    both readers (XboxModelReader and the mmap stack)."""
    from paddlebox_tpu.serving.store import MmapViewStack, build_stack
    from paddlebox_tpu.train.checkpoint import XboxModelReader
    t = PassTable(table_cfg(), seed=17)
    rng = np.random.RandomState(17)
    base = np.unique(rng.randint(1, 1 << 30, 400).astype(np.uint64))
    drive_pass(t, base)
    flags.set_flag("ckpt_xbox_columnar", False)       # legacy pkl base
    cm = mk_cm(tmp_path, t)
    cm.save_base({}, {}, day="d0")
    flags.set_flag("ckpt_xbox_columnar", True)        # columnar delta
    drive_pass(t, base[: base.size // 4])
    cm.save_delta("d1", delta_id=1)
    root = cm.cfg.xbox_model_dir
    reader = XboxModelReader(root, "d0", "d1")
    assert reader.deltas_applied == 1
    stack, _ = build_stack(root, ["d0", "d1"])
    probe = np.concatenate([base[:64], np.uint64([1, 2, 3])])
    np.testing.assert_array_equal(stack.lookup(probe),
                                  reader.lookup(probe))
    stack.close()


# ------------------------------------------------------------- sharded tier


def test_sharded_view_columnar_load_redistributes_by_policy(tmp_path):
    """A columnar base written under key-mod loads under table-wise: the
    policy-aware ShardedStoreView.load routes every row to its new
    owner, content identical."""
    from paddlebox_tpu.parallel.sharded_table import ShardedPassTable
    cfg = table_cfg()
    t1 = ShardedPassTable(cfg, num_shards=4, bucket_cap=64, seed=1)
    rng = np.random.RandomState(3)
    keys = np.unique(rng.randint(1, 1 << 40, 800).astype(np.uint64))
    vals = rng.rand(keys.size, t1.layout.width).astype(np.float32)
    sv1 = t1.store_view()
    shard = t1.policy.shard_of(keys)
    for s in range(4):
        m = shard == s
        t1.stores[s].assign(keys[m], vals[m])
    man = str(tmp_path / "sh.xman")
    cks.write_sparse_columnar(man, *sv1.state_items(),
                              {"embedx_dim": D,
                               "optimizer": t1.layout.optimizer})

    flags.set_flag("sharding_policy", "table-wise")
    t2 = ShardedPassTable(cfg, num_shards=4, bucket_cap=64, seed=2)
    t2.store_view().load(man)
    shard2 = t2.policy.shard_of(keys)
    for s in range(4):
        m = shard2 == s
        got_k, _ = t2.stores[s].state_items()
        assert set(got_k.tolist()) == set(keys[m].tolist())
    k2, v2 = sorted_items(t2.store_view())
    order = np.argsort(keys)
    np.testing.assert_array_equal(k2, keys[order])
    np.testing.assert_array_equal(v2, vals[order])
