"""Ring / Ulysses attention vs a single-device full-attention oracle on the
8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.mesh import device_mesh_1d
from paddlebox_tpu.parallel.ring_attention import (ring_attention,
                                                   ulysses_attention)

B, T, H, Dh = 2, 32, 8, 16  # T global, sharded over 8 devices → T_local=4


def full_attention(q, k, v, causal):
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(Dh)
    if causal:
        pos = np.arange(T)
        mask = pos[None, None, :, None] >= pos[None, None, None, :]
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture
def qkv():
    rng = np.random.RandomState(0)
    return [rng.randn(B, T, H, Dh).astype(np.float32) for _ in range(3)]


@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(qkv, impl, causal):
    q, k, v = qkv
    mesh = device_mesh_1d(8)
    spec = P(None, "dp")  # shard the sequence axis

    fn = jax.jit(jax.shard_map(
        lambda q, k, v: impl(q, k, v, "dp", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    out = np.asarray(fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    want = full_attention(q, k, v, causal)
    np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_differentiable(qkv):
    q, k, v = qkv
    mesh = device_mesh_1d(8)
    spec = P(None, "dp")

    def loss(q, k, v):
        out = jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "dp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
        return (out ** 2).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for arr in g:
        a = np.asarray(arr)
        assert np.isfinite(a).all()
        assert np.abs(a).sum() > 0

    # parity with the same loss through full attention on one device
    def loss_ref(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(Dh)
        pos = jnp.arange(T)
        s = jnp.where(pos[None, None, :, None] >= pos[None, None, None, :],
                      s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return (jnp.einsum("bhqk,bkhd->bqhd", p, v) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
