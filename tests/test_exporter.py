"""Live ops endpoint (round 18): HTTP contract, scrape safety, satellites.

Pins the exporter's endpoint contracts (content types, Prometheus
exposition shape, per-rank port offset, 404), the degrade paths
(port-in-use warns + disables, flag 0 closes), scrape-under-load, the
StepReporter.peek deep-copy satellite, and trace_stitch's postmortem
mode over flight segment dirs.
"""

import json
import socket
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.metrics import drift as drift_mod
from paddlebox_tpu.metrics import quality as quality_mod
from paddlebox_tpu.metrics.quality import TaggedQuality
from paddlebox_tpu.obs import exporter as exporter_mod
from paddlebox_tpu.obs import flight
from paddlebox_tpu.obs.exporter import (PROM_CONTENT_TYPE, ObsExporter,
                                        render_prometheus)
from paddlebox_tpu.obs.report import ListSink, StepReporter
from paddlebox_tpu.utils.stats import (StatRegistry, gauge_set,
                                       hist_observe, stat_add)


@pytest.fixture
def registry():
    reg = StatRegistry.instance()
    saved = reg.snapshot_all()
    reg.reset()
    yield reg
    reg.reset()
    for k, v in saved["counters"].items():
        reg.set(k, v)
    for k, v in saved["gauges"].items():
        reg.set_gauge(k, v)


@pytest.fixture
def exporter():
    exp = ObsExporter(port=0)       # ephemeral port, direct construction
    yield exp
    exp.close()


def _get(exp, path, timeout=5.0):
    r = urllib.request.urlopen(
        "http://127.0.0.1:%d%s" % (exp.port, path), timeout=timeout)
    return r.status, r.headers.get("Content-Type", ""), r.read()


# ------------------------------------------------------------ endpoints

def test_metrics_exposition_contract(registry, exporter):
    stat_add("reqs_total", 7)
    gauge_set("depth_gauge", 2.5)
    for v in (3.0, 100.0, 9000.0):
        hist_observe("lat_us", v)
    status, ctype, body = _get(exporter, "/metrics")
    assert status == 200
    assert ctype == PROM_CONTENT_TYPE
    text = body.decode()
    assert "# TYPE pbtpu_reqs_total counter" in text
    assert "pbtpu_reqs_total 7" in text
    assert "# TYPE pbtpu_depth_gauge gauge" in text
    assert "pbtpu_depth_gauge 2.5" in text
    # histogram: cumulative buckets ending at +Inf == count, plus
    # percentile gauges
    assert "# TYPE pbtpu_lat_us histogram" in text
    assert 'pbtpu_lat_us_bucket{le="+Inf"} 3' in text
    assert "pbtpu_lat_us_count 3" in text
    assert "pbtpu_lat_us_p99" in text
    # every non-comment line is "name[{labels}] value"
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name, val = ln.rsplit(" ", 1)
        assert name.startswith("pbtpu_")
        float(val)


def test_metrics_carries_quality_and_drift(registry, exporter):
    rng = np.random.RandomState(0)
    q = TaggedQuality(table_size=512)
    pred = rng.rand(500)
    q.add(pred, (rng.rand(500) < pred).astype(int))
    quality_mod.set_active(q)
    m = drift_mod.set_active_new()
    from tests.test_quality import _block
    m.observe_block(_block(seed=1))
    m.roll()
    q.publish_gauges()      # plain quality_auc/copc gauges land too
    try:
        _, _, body = _get(exporter, "/metrics")
        text = body.decode()
        assert 'pbtpu_quality_auc{tag="all"}' in text
        assert 'pbtpu_quality_copc{tag="all"}' in text
        assert 'pbtpu_slot_actual_ctr{slot=' not in text  # no slot adds
        assert "pbtpu_data_drift_score 0" in text
        # Prometheus conformance: one TYPE line per family, and the
        # quality/drift families appear exactly once even though plain
        # gauges of the same names sit in the StatRegistry (a second
        # TYPE — or an interleaved family — is a hard parse error)
        type_names = [ln.split()[2] for ln in text.splitlines()
                      if ln.startswith("# TYPE ")]
        assert len(type_names) == len(set(type_names)), type_names
        auc_samples = [ln for ln in text.splitlines()
                       if ln.startswith("pbtpu_quality_auc")]
        assert auc_samples == ['pbtpu_quality_auc{tag="all"} %.9g'
                               % q.compute()["auc"]]
        _, _, qbody = _get(exporter, "/quality")
        qd = json.loads(qbody)
        assert qd["quality"]["tags"]["all"]["auc"] == \
            q.compute()["auc"]
        assert qd["drift"]["windows"] == 1
    finally:
        quality_mod.set_active(None)
        drift_mod.set_active(None)


def test_report_health_stacks_flight_endpoints(registry, exporter,
                                               tmp_path):
    rep = StepReporter(rank=0, every=1, sink=ListSink())
    rep.note_examples(5)
    rep.maybe_report(1)
    exporter.bind(reporter=rep)
    status, ctype, body = _get(exporter, "/report")
    assert status == 200 and ctype.startswith("application/json")
    doc = json.loads(body)
    assert doc["report"]["step"] == 1
    # no aggregator → own-liveness health answer
    status, _, body = _get(exporter, "/health")
    h = json.loads(body)
    assert status == 200 and h["type"] == "rank_liveness"
    assert h["last_report_step"] == 1
    # stacks: every thread, plain text, contains this thread's frame
    status, ctype, body = _get(exporter, "/stacks")
    assert status == 200 and ctype.startswith("text/plain")
    assert b"MainThread" in body
    # flight: inactive → {"active": false}; active → segments + tail
    status, _, body = _get(exporter, "/flight")
    assert not json.loads(body)["active"]
    prev = flight.set_active(None)
    fr = flight.FlightRecorder(str(tmp_path / "fl"), rank=0)
    flight.set_active(fr)
    try:
        fr.record("beat", label="x")
        status, _, body = _get(exporter, "/flight")
        doc = json.loads(body)
        assert doc["active"] and len(doc["segments"]) == 1
        assert any('"type": "beat"' in ln for ln in doc["tail"])
    finally:
        flight.set_active(prev)
        fr.close()
    # root lists the endpoints; unknown paths 404
    status, _, body = _get(exporter, "/")
    assert "/metrics" in json.loads(body)["endpoints"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(exporter, "/nope")
    assert ei.value.code == 404


def test_health_serves_cluster_record_behind_aggregator(registry,
                                                        exporter):
    from paddlebox_tpu.obs.aggregate import ClusterAggregator
    from paddlebox_tpu.obs.health import HealthMonitor

    class _NullTransport:
        def publish(self, payload):
            pass

        def drain(self):
            return []

    agg = ClusterAggregator(_NullTransport(), rank=0, world=2,
                            health=HealthMonitor(2))
    rep = StepReporter(rank=0, every=1, sink=ListSink(), aggregator=agg)
    rep.note_examples(1)
    rep.maybe_report(1)
    exporter.bind(reporter=rep)
    _, _, body = _get(exporter, "/health")
    h = json.loads(body)
    assert h["type"] == "cluster_health"
    assert set(h["ranks"]) == {"0", "1"}
    assert all("score" in e for e in h["ranks"].values())
    # rank 1 never published: stale path exercised through the merge
    assert h["ranks"]["1"]["stale_windows"] >= 1


def test_scrape_under_load(registry, exporter):
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            stat_add("hammered")
            hist_observe("hammer_us", 7.0)

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(25):
            status, _, body = _get(exporter, "/metrics")
            assert status == 200
            assert b"pbtpu_hammered" in body
    finally:
        stop.set()
        for t in threads:
            t.join()


# --------------------------------------------------------- flag plumbing

def test_flag_lifecycle_and_rank_port_offset(registry):
    base = _free_port_base()
    flags.set_flag("obs_http_port", base)
    e0 = exporter_mod.ensure_from_flags(rank=0)
    assert e0 is not None and e0.port == base
    assert exporter_mod.ensure_from_flags(rank=0) is e0     # reuse
    e1 = exporter_mod.ensure_from_flags(rank=1)             # rank swap
    assert e1 is not e0 and e1.port == base + 1
    assert _get_port(e1.port, "/metrics")[0] == 200
    flags.set_flag("obs_http_port", 0)
    assert exporter_mod.ensure_from_flags() is None
    assert exporter_mod.active() is None


def test_port_in_use_warns_and_disables(registry, capsys):
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    port = sock.getsockname()[1]
    try:
        flags.set_flag("obs_http_port", port)
        assert exporter_mod.ensure_from_flags(rank=0) is None
        err = capsys.readouterr().err
        assert "obs http exporter disabled" in err
        # the degrade is counted where the health plane can see it
        assert StatRegistry.instance().get("log_warning_lines") >= 1
    finally:
        sock.close()
        flags.set_flag("obs_http_port", 0)
        exporter_mod.ensure_from_flags()


def _free_port_base(span: int = 4) -> int:
    """A base port with `span` free consecutive ports (best effort)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    base = s.getsockname()[1]
    s.close()
    return base


def _get_port(port, path):
    r = urllib.request.urlopen("http://127.0.0.1:%d%s" % (port, path),
                               timeout=5)
    return r.status, r.read()


# ------------------------------------------------------------ satellites

def test_peek_returns_deep_copy(registry):
    rep = StepReporter(rank=0, every=1, sink=ListSink())
    rep.note_examples(3)
    rep.maybe_report(1, extra={"nested": {"k": [1, 2]}})
    seen = rep.peek()
    assert seen["nested"]["k"] == [1, 2]
    # consumer mutation must not reach reporter state
    seen["nested"]["k"].append(99)
    seen["step"] = 777
    again = rep.peek()
    assert again["nested"]["k"] == [1, 2]
    assert again["step"] == 1
    assert rep.last_report["nested"]["k"] == [1, 2]
    assert rep.peek() is not rep.last_report


def test_trace_stitch_postmortem_from_flight_dir(tmp_path, registry):
    """Two ranks' flight segments (spans records with a shared trace
    id) stitch into one timeline with a cross-rank flow — no live
    chrome export involved (the SIGKILL postmortem path)."""
    from paddlebox_tpu.obs.tracer import get_tracer
    from tools.trace_stitch import docs_from_flight_dir, main as stitch_main

    d = str(tmp_path / "flightdir")
    prev = flight.set_active(None)
    tracer = get_tracer()
    try:
        for rank in (0, 1):
            fr = flight.FlightRecorder(d, rank=rank)
            tracer.clear()
            t0 = __import__("time").perf_counter()
            tracer.record_span("exchange_r%d" % rank, t0, t0 + 0.01,
                               trace=0xABC0 + 7)      # SHARED id
            tracer.record_span("local_only_r%d" % rank, t0, t0 + 0.002)
            fr.on_report({"type": "step_report", "step": 1, "rank": rank})
            fr.close()
    finally:
        tracer.clear()
        flight.set_active(prev)
    docs = docs_from_flight_dir(d)
    assert len(docs) == 2
    for doc in docs:
        assert doc["metadata"]["postmortem"]
        assert doc["metadata"]["clock_origin_unix_s"] > 0
        assert any(e.get("ph") == "X" for e in doc["traceEvents"])
    out = str(tmp_path / "stitched.json")
    rc = stitch_main([d, "-o", out])
    assert rc == 0                      # cross-rank flow found
    stitched = json.load(open(out))
    flows = [e for e in stitched["traceEvents"]
             if e.get("cat") == "trace"]
    assert len(flows) >= 2
    assert {e["pid"] for e in flows} == {0, 1}
    # an empty dir is a loud exit-2, not a silent zero-flow stitch
    empty = str(tmp_path / "empty")
    __import__("os").makedirs(empty)
    assert stitch_main([empty, "-o", out]) == 2


@pytest.mark.slow
def test_ops_real_cluster():
    """The round-18 acceptance scenario on a REAL 2-process cluster:
    /metrics curl-able on both ranks, /health on rank 0 with per-rank
    scores, and an injected slot drop driving the victim below the
    healthy bar within 2 report windows (tools/ops_cluster_probe.py)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-u",
         os.path.join(repo, "tools", "ops_cluster_probe.py"),
         "--port", "19765"],
        capture_output=True, text=True, timeout=280, cwd=repo)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    last = json.loads(r.stdout.strip().splitlines()[-1])
    assert last["all_ok"] is True
    assert last["windows_to_unhealthy"] <= 2
