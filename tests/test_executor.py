"""Executor facade + fleet-executor interceptor pipeline (roles of
trainer_factory.cc / executor.cc and distributed/fleet_executor/)."""

import numpy as np
import pytest

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
from paddlebox_tpu.fleet.executor import (Carrier, FleetExecutor,
                                          Interceptor, InterceptorMessage)
from paddlebox_tpu.models import CtrDnn
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.train.factory import Executor, create_trainer


def test_trainer_factory_names(tmp_path):
    files, feed = write_synthetic_ctr_files(
        str(tmp_path), num_files=1, lines_per_file=64, num_slots=3,
        vocab_per_slot=40, seed=4)
    feed = type(feed)(slots=feed.slots, batch_size=16)
    tcfg = TableConfig(embedx_dim=4, optimizer=SparseOptimizerConfig(
        mf_create_thresholds=0.0))
    model = CtrDnn(ModelSpec(num_slots=3, slot_dim=7), hidden=(8,))

    exe = Executor()
    tr = exe.init_for_dataset("BoxPSTrainer", model, tcfg, feed,
                              TrainerConfig(dense_lr=0.01))
    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    stats = exe.train_from_dataset(tr, ds)
    assert stats["instances"] == 64
    preds, labels = exe.infer_from_dataset(tr, ds)
    assert preds.shape == labels.shape and preds.size == 64
    exe.close()

    with pytest.raises(KeyError):
        create_trainer("NoSuchTrainer")


def test_interceptor_pipeline_single_carrier():
    """3-stage pipeline: source ×2 → +10 → sink (the compute-interceptor
    chain shape of carrier.cc)."""
    ex = FleetExecutor()
    c = ex.carrier

    def stage_double(it, msg):
        it.send(2, msg.payload * 2)

    def stage_add(it, msg):
        it.send(3, msg.payload + 10)

    c.add_interceptor(Interceptor(1, stage_double))
    c.add_interceptor(Interceptor(2, stage_add))
    ex.add_sink(3, expect=5)
    out = ex.run(1, [1, 2, 3, 4, 5], timeout=20)
    assert sorted(out) == [12, 14, 16, 18, 20]
    c.stop()


def test_interceptor_pipeline_cross_carrier():
    """Stage 2 lives on a second carrier reached over the TCP message bus
    (message_bus.cc role)."""
    ex = FleetExecutor()
    c1 = ex.carrier
    c2 = Carrier(carrier_id=1)

    def stage1(it, msg):
        it.send(20, msg.payload + 1)     # remote

    def stage2(it, msg):
        it.send(30, msg.payload * 3)     # remote back to c1

    c1.add_interceptor(Interceptor(10, stage1))
    c2.add_interceptor(Interceptor(20, stage2))
    ex.add_sink(30, expect=4)
    c1.register_route(20, "127.0.0.1", c2.port)
    c2.register_route(30, "127.0.0.1", c1.port)
    out = ex.run(10, [0, 1, 2, 3], timeout=20)
    assert sorted(out) == [3, 6, 9, 12]
    c1.stop()
    c2.stop()


def test_factory_resolves_round2_trainer_names():
    """PSGPUTrainer builds the PS-backed sharded trainer; Heter/Downpour
    names resolve (trainer_factory.cc:68-89 registry parity)."""
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.fleet.heter import HeterTrainer
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.parallel.mesh import device_mesh_1d
    from paddlebox_tpu.ps import PsLocalClient
    from paddlebox_tpu.ps.worker import DownpourTrainer

    feed = default_feed_config(num_slots=2, batch_size=16, max_len=2)
    tcfg = TableConfig(embedx_dim=4, pass_capacity=8 * 64,
                       optimizer=SparseOptimizerConfig())
    cl = PsLocalClient()
    cl.create_sparse_table(0, tcfg, shard_num=8, seed=0)
    tr = create_trainer(
        "PSGPUTrainer",
        CtrDnn(ModelSpec(num_slots=2, slot_dim=7), hidden=(8,)),
        tcfg, feed, TrainerConfig(), mesh=device_mesh_1d(8),
        ps_client=cl, ps_table_id=0)
    from paddlebox_tpu.embedding.ps_store import PSBackedStore
    assert isinstance(tr.table.stores[0], PSBackedStore)
    with pytest.raises(ValueError):
        create_trainer("PSGPUTrainer",
                       CtrDnn(ModelSpec(num_slots=2, slot_dim=7),
                              hidden=(8,)),
                       tcfg, feed, TrainerConfig())
    assert _builtin_resolves("HeterTrainer") is HeterTrainer
    assert _builtin_resolves("DownpourTrainer") is DownpourTrainer
    # HeterXpuTrainer keeps its accelerator-side mapping
    from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
    assert _builtin_resolves("HeterXpuTrainer") is ShardedBoxTrainer


def _builtin_resolves(name):
    from paddlebox_tpu.train import factory
    return factory._builtin(name)
