"""Serving handoff demo on the round-12 serving plane.

Default (demo) role — the dryrun leg, end to end on one box:
train day0 (run_day: cadenced delta saves + base save), bring up a
ServingServer over the day's xbox output (mmap view stack + hot-key
cache + delta-refresh watcher), pull embeddings through the
plain-container RPC client, check bit-parity against the XboxModelReader
oracle, then land a MID-DAY day1 SaveDelta and watch the served vectors
refresh within one poll interval.

    JAX_PLATFORMS=cpu python examples/serve_xbox.py

Deployment roles (the same modules, split across boxes):

    # loader/serving box (N replica processes):
    python examples/serve_xbox.py --role server --root /path/xbox \
        --days day0,day1 --processes 2
    # any client box:
    python examples/serve_xbox.py --role client \
        --endpoints host:port,host:port --keys 123,456
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def role_server(args) -> None:
    """Serving fleet on the store root (jax never imports here)."""
    from paddlebox_tpu.serving import ServingFleet
    days = args.days.split(",") if args.days else None
    with ServingFleet(args.root, days=days,
                      processes=args.processes) as fleet:
        print("serving fleet up:", fleet.endpoints, flush=True)
        try:
            while True:
                time.sleep(60)
        except KeyboardInterrupt:
            print("draining fleet")


def role_client(args) -> None:
    import numpy as np

    from paddlebox_tpu.serving import ServingClient
    eps = [(h, int(p)) for h, p in
           (e.split(":") for e in args.endpoints.split(","))]
    client = ServingClient(eps)
    keys = np.array([int(k) for k in args.keys.split(",")], np.uint64)
    emb = client.pull(keys)
    print(f"serving gen {client.last_gen}")
    for k, row in zip(keys.tolist(), emb):
        print(f"  feasign {k}: embed_w={row[0]:+.4f} "
              f"embedx={np.round(row[1:4], 4)}...")
    client.close()


def role_demo(args) -> None:
    import numpy as np

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (CheckpointConfig,
                                              SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.serving import ServingClient, ServingServer
    from paddlebox_tpu.train import BoxTrainer, CheckpointManager
    from paddlebox_tpu.train.checkpoint import XboxModelReader, run_day

    import tempfile

    work = tempfile.mkdtemp(prefix="pbx_serve_")
    files, feed = write_synthetic_ctr_files(
        os.path.join(work, "data"), num_files=2, lines_per_file=800,
        num_slots=8, vocab_per_slot=400, max_len=4, seed=9)
    feed = type(feed)(slots=feed.slots, batch_size=128)

    D = 8
    table = TableConfig(
        embedx_dim=D, pass_capacity=1 << 15,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    trainer = BoxTrainer(
        CtrDnn(ModelSpec(num_slots=8, slot_dim=3 + D), hidden=(64, 32)),
        table, feed, TrainerConfig(dense_lr=1e-3), seed=0)
    cm = CheckpointManager(
        CheckpointConfig(batch_model_dir=os.path.join(work, "batch"),
                         xbox_model_dir=os.path.join(work, "xbox"),
                         async_save=False, save_delta_every_passes=1),
        trainer.table)
    dss = []
    for _ in range(args.passes):
        ds = BoxDataset(feed, read_threads=2)
        ds.set_filelist(files)
        dss.append(ds)
    stats, (batch_dir, xbox_dir) = run_day(trainer, dss, cm, day="day0")
    print(f"trained day0: {len(stats)} passes, final loss "
          f"{stats[-1]['loss']:.4f}")

    xbox_root = os.path.dirname(xbox_dir)
    reader = XboxModelReader(xbox_root, "day0")
    print(f"serving view: {len(reader)} features x {reader.dim} cols "
          f"({reader.deltas_applied} deltas composed)")

    # ---- serving tier: mmap view stack + cache + RPC behind one server
    flags.set_flag("serving_refresh_secs", 0.2)
    flags.set_flag("serving_report_requests", 2)  # demo-size obs cadence
    # days auto-discover each poll: day1's streaming deltas join the
    # composition the moment their DONE markers land
    server = ServingServer(xbox_root)
    client = ServingClient([("127.0.0.1", server.port)])
    from paddlebox_tpu.serving.store import read_xbox_view
    keys = np.asarray(read_xbox_view(xbox_dir)[0][:64], np.uint64)
    t0 = time.perf_counter()
    emb = client.pull(keys)
    dt = time.perf_counter() - t0
    assert np.array_equal(emb, reader.lookup(keys)), \
        "served vectors must be bit-identical to the XboxModelReader oracle"
    print(f"pull RPC: {keys.size} keys in {dt * 1e3:.2f} ms "
          f"(gen {client.last_gen}), oracle parity OK")
    for k, row in zip(keys[:3].tolist(), emb):
        print(f"  feasign {k}: embed_w={row[0]:+.4f} "
              f"embedx={np.round(row[1:4], 4)}...")

    # ---- mid-day refresh: land a day1 SaveDelta while serving
    ds = BoxDataset(feed, read_threads=2)
    ds.set_filelist(files[:1])
    trainer.train_pass(ds)
    ds.release_memory()
    cm.save_delta("day1", 1)
    cm.wait()
    oracle2 = XboxModelReader(xbox_root, "day0", "day1")
    deadline = time.time() + 10.0
    while time.time() < deadline:
        emb2 = client.pull(keys)
        if np.array_equal(emb2, oracle2.lookup(keys)):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("day1 delta not served within 10s")
    changed = int((emb2 != emb).any(axis=1).sum())
    print(f"delta refresh: day1 SaveDelta visible in served vectors "
          f"(gen {client.last_gen}, {changed}/{keys.size} keys changed), "
          f"oracle parity OK")
    st = client.stats()
    rep = st["last_report"] or {}
    hists = rep.get("hists", {}).get("serving_lookup_us", {})
    print(f"obs: {st['requests']} pulls, cache {st['cache_hit']} hit / "
          f"{st['cache_miss']} miss, lookup p50={hists.get('p50')}us "
          f"p99={hists.get('p99')}us")
    client.close()
    server.drain()
    trainer.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", choices=("demo", "server", "client"),
                    default="demo")
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--root", help="xbox model root (server role)")
    ap.add_argument("--days", default="",
                    help="comma-separated day dirs in cadence order "
                         "(default: auto-discover)")
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--endpoints", default="",
                    help="host:port,host:port (client role)")
    ap.add_argument("--keys", default="1,2,3")
    args = ap.parse_args()
    if args.role == "server":
        role_server(args)
    elif args.role == "client":
        role_client(args)
    else:
        role_demo(args)


if __name__ == "__main__":
    main()
