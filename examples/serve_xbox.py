"""Serving handoff demo: train a day, then serve from the xbox views.

Runs the full day cadence (run_day: cadenced delta saves + base save +
day-boundary aging), then loads the day's xbox output with
XboxModelReader — the consumer role of the external serving loader that
ingests SaveBase/SaveDelta — and answers embedding lookups from it.

    JAX_PLATFORMS=cpu python examples/serve_xbox.py
"""

import argparse
import os
import pickle
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=2)
    args = ap.parse_args()

    import numpy as np

    from paddlebox_tpu.config.configs import (CheckpointConfig,
                                              SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train import BoxTrainer, CheckpointManager
    from paddlebox_tpu.train.checkpoint import XboxModelReader, run_day

    work = tempfile.mkdtemp(prefix="pbx_serve_")
    files, feed = write_synthetic_ctr_files(
        os.path.join(work, "data"), num_files=2, lines_per_file=800,
        num_slots=8, vocab_per_slot=400, max_len=4, seed=9)
    feed = type(feed)(slots=feed.slots, batch_size=128)

    D = 8
    table = TableConfig(
        embedx_dim=D, pass_capacity=1 << 15,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    trainer = BoxTrainer(
        CtrDnn(ModelSpec(num_slots=8, slot_dim=3 + D), hidden=(64, 32)),
        table, feed, TrainerConfig(dense_lr=1e-3), seed=0)
    cm = CheckpointManager(
        CheckpointConfig(batch_model_dir=os.path.join(work, "batch"),
                         xbox_model_dir=os.path.join(work, "xbox"),
                         async_save=False, save_delta_every_passes=1),
        trainer.table)
    dss = []
    for _ in range(args.passes):
        ds = BoxDataset(feed, read_threads=2)
        ds.set_filelist(files)
        dss.append(ds)
    stats, (batch_dir, xbox_dir) = run_day(trainer, dss, cm, day="day0")
    print(f"trained day0: {len(stats)} passes, final loss "
          f"{stats[-1]['loss']:.4f}")
    trainer.close()

    xbox_root = os.path.dirname(xbox_dir)
    reader = XboxModelReader(xbox_root, "day0")
    print(f"serving view: {len(reader)} features x {reader.dim} cols "
          f"({reader.deltas_applied} deltas composed)")
    # sample keys from the SERVING artifact itself (the xbox base view —
    # the file serving consumers actually ingest)
    with open(os.path.join(xbox_dir, "embedding.pkl"), "rb") as f:
        keys = pickle.load(f)["keys"][:5]
    emb = reader.lookup(np.asarray(keys, np.uint64))
    for k, row in zip(keys.tolist(), emb):
        print(f"  feasign {k}: embed_w={row[0]:+.4f} "
              f"embedx={np.round(row[1:4], 4)}...")

    # serving-scale tier (round 5): compile the composed view into the
    # columnar store file and serve it via mmap + the native hash index
    # — no row-matrix RAM ingest (10.75M keys/s hot at a 30M-key base,
    # BASELINE.md round-5 xbox table)
    from paddlebox_tpu.train.checkpoint import MmapXboxStore
    store_path = reader.save_columnar(os.path.join(work, "serve.xbox"))
    store = MmapXboxStore(store_path)
    mm = store.lookup(np.asarray(keys, np.uint64))
    assert np.array_equal(mm, emb), "mmap store must match the reader"
    print(f"mmap store: {len(store)} features served from "
          f"{os.path.getsize(store_path) >> 20} MB file "
          f"(native_index={store._index is not None})")
    store.close()


if __name__ == "__main__":
    main()
