"""Downpour CPU-PS training demo: the DistMultiTrainer/DownpourWorker path.

Workers pull sparse rows per batch from a distributed CPU parameter server,
push merged gradients through a Communicator (async grad aggregation), and
refresh dense params via a background PullDenseWorker — the CPU analog of
the reference's downpour_worker.cc TrainFiles loop over the-one-ps tables.

    python examples/train_downpour.py [--passes 4] [--tcp] [--async-comm]

--tcp brings up a real PS server on 127.0.0.1 and trains over the wire;
the default uses the in-process PsLocalClient (SURVEY §4's two test
mechanisms).
"""

import argparse
import dataclasses
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--tcp", action="store_true",
                    help="train against a real TCP PS server")
    ap.add_argument("--async-comm", action="store_true",
                    help="asynchronous Communicator sends (default sync)")
    args = ap.parse_args()

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.metrics.auc import BasicAucCalculator
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.ps import PSServer, PsLocalClient, TcpPSClient
    from paddlebox_tpu.ps.worker import DownpourTrainer

    data_dir = tempfile.mkdtemp(prefix="pbx_downpour_")
    files, feed = write_synthetic_ctr_files(
        data_dir, num_files=2, lines_per_file=500, num_slots=8,
        vocab_per_slot=300, max_len=3, seed=13)
    feed = dataclasses.replace(feed, batch_size=64)

    D = 4
    table = TableConfig(embedx_dim=D, optimizer=SparseOptimizerConfig(
        mf_create_thresholds=0.0, mf_initial_range=1e-3,
        feature_learning_rate=0.2, mf_learning_rate=0.2))

    server = None
    if args.tcp:
        server = PSServer()
        client = TcpPSClient("127.0.0.1", server.port)
        print(f"TCP PS on 127.0.0.1:{server.port}")
    else:
        client = PsLocalClient()

    tr = DownpourTrainer(
        CtrDnn(ModelSpec(num_slots=8, slot_dim=3 + D), hidden=(32, 16)),
        table, feed, client, TrainerConfig(dense_lr=0.01),
        sync_comm=not args.async_comm)
    tr.metrics.init_metric("auc", "label", "pred", mask_var="mask")

    for i in range(args.passes):
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        stats = tr.train_pass(ds)
        print(f"pass {i}: loss={stats['loss']:.4f}")

    ds = BoxDataset(feed, read_threads=1)
    ds.set_filelist(files)
    preds, labels = tr.predict_pass(ds)
    calc = BasicAucCalculator(1 << 14)
    calc.add_data(preds, labels)
    calc.compute()
    print(f"eval AUC: {calc.auc():.4f}  rows on PS: "
          f"{client.sparse_size(DownpourTrainer.SPARSE_TABLE)}")
    tr.close()
    if server is not None:
        client.stop_server()
        client.close()


if __name__ == "__main__":
    main()
