"""Pipeline-parallel CTR training demo: the program split across stages.

The reference's HeterPipelineTrainer/SectionWorker capability
(optimizer.py:7496-7575 cut_list → section_worker.cc) as one SPMD
program: stage 0 owns the sparse section (pull → fused seqpool+CVM →
input projection), every stage owns a block of the deep tower, the last
stage owns the head and the loss; micro-batches flow on the ppermute ring
and gradients flow back through the transposed pipeline into the
in-table sparse optimizer.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_pipeline.py --passes 4 [--stages 4]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--stages", type=int, default=0,
                    help="pipeline stages (default: all devices)")
    ap.add_argument("--micro", type=int, default=0,
                    help="micro-batches per step (default: 2 x stages)")
    ap.add_argument("--sharded-slab", action="store_true",
                    help="key-mod-shard the pass table over the stage "
                         "devices (O(pass/P) table memory per device) "
                         "instead of replicating it")
    args = ap.parse_args()

    import jax

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.train.factory import create_trainer

    S = args.stages or len(jax.devices())
    print(f"pipeline: {S} stages × {jax.devices()[0].platform}")
    data_dir = tempfile.mkdtemp(prefix="pbx_pipe_")
    files, feed = write_synthetic_ctr_files(
        data_dir, num_files=4, lines_per_file=800, num_slots=8,
        vocab_per_slot=500, max_len=4, seed=7)
    feed = type(feed)(slots=feed.slots, batch_size=64)

    D = 8
    table = TableConfig(
        embedx_dim=D, pass_capacity=1 << 15,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    # the factory resolves the reference trainer name to the CTR program
    # split (trainer_factory.cc name surface); --sharded-slab picks the
    # composition over the full key-mod-sharded PS (section_worker.cc
    # sections against the sharded table)
    name = ("ShardedCtrPipelineTrainer" if args.sharded_slab
            else "HeterPipelineTrainer")
    runner = create_trainer(name, table, feed,
                            n_stages=S, d_model=64, layers_per_stage=1,
                            lr=5e-3, n_micro=args.micro or 2 * S, seed=0)

    for i in range(args.passes):
        ds = BoxDataset(feed, read_threads=2)
        ds.set_filelist(files)
        stats = runner.train_pass(ds)
        print(f"pass {i}: loss={stats['loss']:.4f} steps={stats['steps']} "
              f"(dropped {stats['dropped_batches']} tail batches)")
        ds.release_memory()
    if args.sharded_slab:
        keys, _ = runner.table.store_view().state_items()
        print(f"features trained: {keys.size} across "
              f"{runner.table.num_shards} shards "
              f"(shard slab {runner.table.shard_cap} rows)")
    else:
        keys, _ = runner.table.store.state_items()
        print("features trained:", keys.size)


if __name__ == "__main__":
    main()
