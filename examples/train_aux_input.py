"""Aux side-table demo: InputTable rows consumed through the feed path.

The InputTableDataFeed / lookup_input composition (data_feed.h:2221-2252;
pull_box_sparse_op.cc:173-208): training lines lead with an instance id
(`parse_ins_id`), the feed translates each id to an aux-row offset at pack
time, and the model gathers the frozen rows on device. Here the click
signal depends on a per-item attribute that lives ONLY in the aux table,
so the lift over the no-table run is the capability demonstrated.

    JAX_PLATFORMS=cpu python examples/train_aux_input.py [--passes 4]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def write_files(out_dir: str, n_lines: int, n_items: int, num_slots: int,
                vocab: int, seed: int):
    """ins_id-prefixed MultiSlot lines; click driven by the item group."""
    rng = np.random.RandomState(seed)
    groups = (np.arange(n_items) % 2).astype(np.float32)
    path = os.path.join(out_dir, "part-00000.txt")
    with open(path, "w") as f:
        for _ in range(n_lines):
            item = rng.randint(n_items)
            click = int(rng.rand() < (0.85 if groups[item] else 0.15))
            toks = [f"item{item}", f"1 {click}"]
            for si in range(num_slots):
                n = rng.randint(1, 4)
                feas = rng.randint(0, vocab, n) + si * vocab
                toks.append(str(n) + " " + " ".join(map(str, feas)))
            f.write(" ".join(toks) + "\n")
    return [path], groups


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=4)
    args = ap.parse_args()

    from paddlebox_tpu.config.configs import (DataFeedConfig, SlotConfig,
                                              SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.embedding.side_tables import InputTable
    from paddlebox_tpu.models.aux_input import CtrDnnAux
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train.trainer import BoxTrainer

    NUM_SLOTS, VOCAB, AUX_DIM, N_ITEMS = 4, 200, 8, 16
    slots = [SlotConfig("click", type="float", dim=1, is_used=False)]
    slots += [SlotConfig(f"slot_{i}", type="uint64", max_len=3)
              for i in range(NUM_SLOTS)]
    feed = DataFeedConfig(slots=tuple(slots), batch_size=64,
                          parse_ins_id=True)
    data_dir = tempfile.mkdtemp(prefix="pbx_aux_")
    files, groups = write_files(data_dir, 2048, N_ITEMS, NUM_SLOTS, VOCAB,
                                seed=3)

    # the serving-side item attribute store (filled by some upstream job)
    aux = InputTable(AUX_DIM)
    rng = np.random.RandomState(0)
    for i in range(N_ITEMS):
        row = rng.randn(AUX_DIM).astype(np.float32) * 0.1
        row[0] = 2.0 * groups[i] - 1.0          # the learnable attribute
        aux.add_index_data(f"item{i}", row)

    table = TableConfig(
        embedx_dim=8, pass_capacity=1 << 14,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    model = CtrDnnAux(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + 8),
                      aux_dim=AUX_DIM, aux_capacity=64, hidden=(64, 32))
    trainer = BoxTrainer(model, table, feed,
                         TrainerConfig(dense_lr=5e-3), seed=0,
                         aux_source=aux)

    for i in range(args.passes):
        ds = BoxDataset(feed, read_threads=1, input_table=aux)
        ds.set_filelist(files)
        stats = trainer.train_pass(ds)
        print(f"pass {i}: loss={stats['loss']:.4f} "
              f"batches={stats['batches']} (aux misses so far {aux.miss})")
        ds.release_memory()
    print(f"aux rows served: {aux.size()} items, dim {AUX_DIM}")


if __name__ == "__main__":
    main()
