"""Streaming continuous training on the round-19 micro-pass plane.

Default (demo) role — the dryrun leg, end to end on one box: a feeder
thread drops MultiSlot files into a watched directory; a
StreamingRunner tails them through the native parser in bounded
micro-passes (window N+1's ingest overlapped with window N's training),
publishing journal segments at every boundary; a serving view
(ViewManager + DeltaRefreshWatcher with a JournalDeltaSource) flips the
served vectors from those segments without waiting on SaveDelta — the
demo measures the ingest-to-serve freshness of a live drop.

    JAX_PLATFORMS=cpu python examples/stream_train_serve.py

Deployment roles (the same modules, split across boxes):

    # upstream feed box: land synthetic drops on the shared source dir
    python examples/stream_train_serve.py --role feed \
        --source /path/stream/source --files 24 --interval 0.5
    # trainer box: tail the source, micro-checkpoint + journal under root
    python examples/stream_train_serve.py --role train \
        --source /path/stream/source --root /path/stream
    # serving box (N replica processes, journal-fed freshness):
    python examples/stream_train_serve.py --role serve \
        --root /path/stream --processes 2
    # any client box:
    python examples/stream_train_serve.py --role client \
        --endpoints host:port,host:port --keys 123,456
"""

import argparse
import os
import shutil
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()

NUM_SLOTS = 4
EMBEDX = 4
VOCAB = 400
BATCH = 64
LINES_PER_FILE = 300


def _make_data(staging, num_files, seed=7):
    from paddlebox_tpu.data import write_synthetic_ctr_files
    files, feed = write_synthetic_ctr_files(
        staging, num_files=num_files, lines_per_file=LINES_PER_FILE,
        num_slots=NUM_SLOTS, vocab_per_slot=VOCAB, max_len=4, seed=seed)
    return files, type(feed)(slots=feed.slots, batch_size=BATCH)


def _make_trainer(feed):
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train import BoxTrainer
    table = TableConfig(
        embedx_dim=EMBEDX, pass_capacity=1 << 14,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    return BoxTrainer(
        CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + EMBEDX),
               hidden=(32, 16)),
        table, feed, TrainerConfig(dense_lr=1e-3), seed=0)


def _make_cm(root, table):
    from paddlebox_tpu.config.configs import CheckpointConfig
    from paddlebox_tpu.train import CheckpointManager
    return CheckpointManager(
        CheckpointConfig(batch_model_dir=os.path.join(root, "batch"),
                         xbox_model_dir=os.path.join(root, "xbox"),
                         async_save=False),
        table)


def _drop(src, source_dir, index):
    """Land one file the way a well-behaved upstream does: write under a
    temp name, fsync, rename into place (the convention the watcher
    trusts — a half-copied file is never ingested)."""
    dst = os.path.join(source_dir, "drop-%04d.txt" % index)
    shutil.copyfile(src, dst + ".tmp")
    os.replace(dst + ".tmp", dst)
    return dst


def role_feed(args) -> None:
    """Upstream stand-in: land synthetic drops on the source dir."""
    import tempfile
    staging = tempfile.mkdtemp(prefix="pbx_feed_")
    files, _ = _make_data(staging, args.files, seed=args.seed)
    os.makedirs(args.source, exist_ok=True)
    for i, f in enumerate(files):
        path = _drop(f, args.source, i + args.start_index)
        print(f"fed {os.path.basename(path)}", flush=True)
        time.sleep(args.interval)
    shutil.rmtree(staging, ignore_errors=True)
    print(f"feed done: {len(files)} files", flush=True)


def role_train(args) -> None:
    """Trainer box: tail the source dir in micro-passes forever (or
    until the stream is idle for --idle seconds)."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.data import StreamingDataset
    from paddlebox_tpu.train import StreamingRunner

    _, feed = _make_data(os.path.join(args.root, "_feedspec"), 1)
    trainer = _make_trainer(feed)
    cm = _make_cm(args.root, trainer.table)
    flags.set_flag("streaming_poll_secs", args.poll_secs)
    stream = StreamingDataset(
        feed, args.source, ledger_dir=os.path.join(args.root, "batch"),
        micro_pass_instances=args.window)
    runner = StreamingRunner(trainer, stream, cm=cm)
    print(f"tailing {args.source}; journal at {cm.journal.dir}", flush=True)
    # bootstrap a servable day the moment the first window lands, so the
    # serve role has a base composition to stack journal freshness onto
    runner.run(max_micro_passes=1, idle_timeout=args.idle)
    cm.save_delta("day0", 0)
    cm.wait()
    print(f"day0 published under {os.path.join(args.root, 'xbox')}",
          flush=True)
    try:
        res = runner.run(idle_timeout=args.idle)
        print(f"stream idle: {res['micro_passes']} micro-passes, "
              f"{res['examples_per_sec']:.0f} ex/s", flush=True)
    except KeyboardInterrupt:
        runner.stop()
        print("trainer draining", flush=True)
    trainer.close()


def role_serve(args) -> None:
    """Serving box: replicas over root/xbox, journal-fed freshness from
    the trainer's touched-row journal (jax never imports here)."""
    from paddlebox_tpu.serving import ServingFleet
    jdir = os.path.join(args.root, "batch", "_journal", "rank0")
    with ServingFleet(os.path.join(args.root, "xbox"),
                      processes=args.processes,
                      flag_overrides={"serving_journal_dir": jdir}) as fleet:
        print("serving fleet up:", fleet.endpoints, flush=True)
        try:
            while True:
                time.sleep(60)
        except KeyboardInterrupt:
            print("draining fleet")


def role_client(args) -> None:
    import numpy as np

    from paddlebox_tpu.serving import ServingClient
    eps = [(h, int(p)) for h, p in
           (e.split(":") for e in args.endpoints.split(","))]
    client = ServingClient(eps)
    keys = np.array([int(k) for k in args.keys.split(",")], np.uint64)
    emb = client.pull(keys)
    print(f"serving gen {client.last_gen}")
    for k, row in zip(keys.tolist(), emb):
        print(f"  feasign {k}: embed_w={row[0]:+.4f} "
              f"embedx={np.round(row[1:4], 4)}...")
    client.close()


def role_demo(args) -> None:
    import numpy as np

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.data import StreamingDataset
    from paddlebox_tpu.serving.refresh import (DeltaRefreshWatcher,
                                               JournalDeltaSource,
                                               make_manager)
    from paddlebox_tpu.serving.store import read_xbox_view
    from paddlebox_tpu.train import StreamingRunner

    import tempfile

    work = tempfile.mkdtemp(prefix="pbx_stream_")
    files, feed = _make_data(os.path.join(work, "staging"), 6)
    flags.set_flag("streaming_poll_secs", 0.05)
    flags.set_flag("dataset_disable_shuffle", True)

    source = os.path.join(work, "source")
    trainer = _make_trainer(feed)
    cm = _make_cm(work, trainer.table)
    stream = StreamingDataset(feed, source,
                              ledger_dir=os.path.join(work, "batch"),
                              micro_pass_instances=2 * LINES_PER_FILE)
    runner = StreamingRunner(trainer, stream, cm=cm, base_every=4)

    # ---- seed: first drop trains one micro-pass and lands the anchor
    for i in (0, 1):
        _drop(files[i], source, i)
    res = runner.run(max_micro_passes=1, idle_timeout=10.0)
    print(f"seed micro-pass: {res['instances']} instances, loss "
          f"{res['passes'][0]['loss']:.4f}", flush=True)
    xdir = cm.save_delta("day0", 0)
    cm.wait()

    # ---- serving tier over the day0 composition + journal overlay
    xroot = os.path.join(work, "xbox")
    manager, sources = make_manager(xroot)
    jsrc = JournalDeltaSource([cm.journal.dir])
    watcher = DeltaRefreshWatcher(manager, xroot, known_sources=sources,
                                  journal=jsrc, poll_secs=0.1).start()
    time.sleep(0.3)  # let the first poll stack the seed journal overlay
    keys = np.asarray(read_xbox_view(xdir)[0][:32], np.uint64)
    baseline, gen0 = manager.lookup(keys)
    print(f"serving view up: {keys.size} probe keys at gen {gen0}",
          flush=True)

    # ---- live leg: feeder drops while the runner micro-passes; a
    # detector thread timestamps the first served-vector change
    detected = {}
    seen = threading.Event()

    def _detect():
        while not seen.is_set():
            emb, gen = manager.lookup(keys)
            if not np.array_equal(emb, baseline):
                detected["ts"] = time.time()
                detected["gen"] = gen
                seen.set()
                return
            time.sleep(0.03)

    drop_ts = {}

    def _feed():
        time.sleep(0.2)
        for i in (2, 3, 4, 5):
            _drop(files[i], source, i)
            drop_ts[i] = time.time()
            time.sleep(0.25)

    det = threading.Thread(target=_detect, daemon=True)
    fed = threading.Thread(target=_feed, daemon=True)
    det.start()
    fed.start()
    res = runner.run(max_micro_passes=2, idle_timeout=8.0)
    fed.join()
    det.join(timeout=10.0)
    seen.set()
    assert "ts" in detected, \
        "served vectors did not flip from the journal overlay within 10s"
    freshness = detected["ts"] - drop_ts[2]
    print(f"live leg: {res['micro_passes']} micro-passes, "
          f"{res['instances']} instances, "
          f"{res['examples_per_sec']:.0f} ex/s, max ingest wait "
          f"{res['max_ingest_wait_secs']:.2f}s", flush=True)
    print(f"ingest-to-serve freshness: {freshness:.2f}s "
          f"(drop -> served gen {detected['gen']}, no SaveDelta in "
          f"between)", flush=True)

    watcher.stop()
    jsrc.close()
    manager.close()
    trainer.close()
    shutil.rmtree(work, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role",
                    choices=("demo", "feed", "train", "serve", "client"),
                    default="demo")
    ap.add_argument("--source", help="watched source dir (feed/train)")
    ap.add_argument("--root", help="model root: batch/ xbox/ land here "
                                   "(train/serve)")
    ap.add_argument("--files", type=int, default=24,
                    help="files to feed (feed role)")
    ap.add_argument("--interval", type=float, default=0.5,
                    help="seconds between fed files")
    ap.add_argument("--start-index", type=int, default=0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--window", type=int, default=2 * LINES_PER_FILE,
                    help="micro-pass instance bound (train role)")
    ap.add_argument("--poll-secs", type=float, default=0.2)
    ap.add_argument("--idle", type=float, default=30.0,
                    help="stop after this many idle seconds (train role)")
    ap.add_argument("--processes", type=int, default=2)
    ap.add_argument("--endpoints", default="",
                    help="host:port,host:port (client role)")
    ap.add_argument("--keys", default="1,2,3")
    args = ap.parse_args()
    if args.role == "feed":
        role_feed(args)
    elif args.role == "train":
        role_train(args)
    elif args.role == "serve":
        role_serve(args)
    elif args.role == "client":
        role_client(args)
    else:
        role_demo(args)


if __name__ == "__main__":
    main()
