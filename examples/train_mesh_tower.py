"""Model-parallel tower demo: TP wide DeepFM or expert-parallel MMoE.

The towers the reference replicates stay small; when a tower does NOT fit
replicated, its wide layer column/row-splits (Megatron) or its expert
blocks shard over a `mp` mesh axis, and MeshTowerTrainer runs the full
sparse hot loop with the TP autodiff contracts enforced in code
(tp_loss_scale + tp_fix_grads — no partial/P-scaled gradients).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_mesh_tower.py --kind tp [--passes 4]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--kind", choices=("tp", "ep"), default="tp")
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--wide", type=int, default=1024,
                    help="TP tower hidden width (splits over the mesh)")
    args = ap.parse_args()

    import jax

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.wide_tower import EpMMoE, TpDeepFM
    from paddlebox_tpu.train.factory import create_trainer

    P = len(jax.devices())
    data_dir = tempfile.mkdtemp(prefix="pbx_mt_")
    files, feed = write_synthetic_ctr_files(
        data_dir, num_files=4, lines_per_file=800, num_slots=8,
        vocab_per_slot=500, max_len=4, seed=11)
    feed = type(feed)(slots=feed.slots, batch_size=128)
    D = 8
    table = TableConfig(
        embedx_dim=D, pass_capacity=1 << 15,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    spec = ModelSpec(num_slots=8, slot_dim=3 + D)
    if args.kind == "tp":
        model = TpDeepFM(spec, n_shards=P, d_wide=args.wide, d_mid=64)
        print(f"TP DeepFM: {args.wide}-wide layer split over {P} devices "
              f"({args.wide // P} columns each)")
    else:
        model = EpMMoE(spec, n_shards=P, n_experts=2 * P, d_hidden=64,
                       d_out=32)
        print(f"EP MMoE: {2 * P} experts over {P} devices (2 each)")
    trainer = create_trainer("MeshTowerTrainer", model, table, feed,
                             TrainerConfig(dense_lr=5e-3), seed=0)

    for i in range(args.passes):
        ds = BoxDataset(feed, read_threads=2)
        ds.set_filelist(files)
        stats = trainer.train_pass(ds)
        print(f"pass {i}: loss={stats['loss']:.4f} "
              f"batches={stats['batches']}")
        ds.release_memory()
    keys, _ = trainer.table.store.state_items()
    print("features trained:", keys.size)


if __name__ == "__main__":
    main()
