"""Multi-chip CTR training demo: key-sharded table over a device mesh.

Runs the pod-sharded trainer (table sharded key % P, pull/push as
all_to_all on ICI, dense grads psum'd) with load(N+1) ∥ train(N) preload
overlap. Works on real chips or on virtual CPU devices:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_sharded.py --passes 3 [--sync k_step|sharding]

For the GPUPS variant (pass slabs built from / dumped to a distributed
CPU PS over TCP), pass --gpups. For a real multi-process cluster, see
tests/multihost_worker.py + paddlebox_tpu.fleet.launch.
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--sync", default="step",
                    choices=["step", "k_step", "sharding"])
    ap.add_argument("--mesh-2d", type=int, default=0, metavar="NODES",
                    help="hierarchical (node, chip) mesh with this many "
                         "node rows: dense sync reduce-scatters on ICI "
                         "and psums 1/chips of the bytes over DCN")
    ap.add_argument("--a2a-dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="wire format of the pull/push value all_to_alls "
                         "(bfloat16 halves the ICI bytes)")
    ap.add_argument("--device-auc", action="store_true",
                    help="accumulate the AUC bucket table inside the "
                         "jitted step (one D2H per pass, no per-step "
                         "prediction transfer)")
    ap.add_argument("--gpups", action="store_true",
                    help="back the shard stores with a TCP CPU PS")
    ap.add_argument("--ssd-budget-mb", type=float, default=0,
                    help="feed-ranking posture: host-DRAM row budget; rows "
                         "beyond it spill to an SSD tier each end_pass")
    args = ap.parse_args()
    if args.mesh_2d:
        import jax as _jax
        if len(_jax.devices()) % args.mesh_2d:
            ap.error(f"--mesh-2d {args.mesh_2d} does not divide "
                     f"{len(_jax.devices())} devices")
    if args.gpups and args.ssd_budget_mb:
        ap.error("--ssd-budget-mb spills the LOCAL host stores; with "
                 "--gpups the stores live on the CPU PS (its tables manage "
                 "their own tiering) — pick one")

    import jax

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models import DeepFM
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.parallel.mesh import device_mesh_1d, device_mesh_2d
    from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer
    from paddlebox_tpu.train.preload import run_preloaded_passes

    P = len(jax.devices())
    print(f"devices: {P} × {jax.devices()[0].platform}")
    data_dir = tempfile.mkdtemp(prefix="pbx_sharded_")
    files, feed = write_synthetic_ctr_files(
        data_dir, num_files=max(4, P), lines_per_file=1000, num_slots=16,
        vocab_per_slot=800, max_len=4, seed=11)
    feed = type(feed)(slots=feed.slots, batch_size=128)

    D = 8
    table = TableConfig(
        embedx_dim=D, pass_capacity=P * (1 << 15),
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3),
        ssd_dir=(os.path.join(data_dir, "ssd") if args.ssd_budget_mb else None),
        ssd_threshold_mb=args.ssd_budget_mb)
    tcfg = TrainerConfig(dense_lr=1e-3, sync_mode=args.sync,
                         sync_weight_step=4 if args.sync == "k_step" else 1,
                         sharding=args.sync == "sharding",
                         a2a_dtype=args.a2a_dtype)

    store_factory = None
    ps_client = None
    if args.gpups:
        from paddlebox_tpu.embedding.ps_store import ps_store_factory
        from paddlebox_tpu.ps import PSServer, TcpPSClient
        server = PSServer()
        ps_client = TcpPSClient("127.0.0.1", server.port)
        ps_client.create_sparse_table(0, table, shard_num=P, seed=0)
        store_factory = ps_store_factory(ps_client, 0)
        print(f"GPUPS mode: CPU PS on 127.0.0.1:{server.port}")

    trainer = ShardedBoxTrainer(
        DeepFM(ModelSpec(num_slots=16, slot_dim=3 + D), hidden=(256, 128)),
        table, feed, tcfg,
        mesh=(device_mesh_2d(args.mesh_2d, P // args.mesh_2d)
              if args.mesh_2d else device_mesh_1d(P)),
        seed=0, store_factory=store_factory)
    trainer.metrics.init_metric("auc", "label", "pred", mask_var="mask",
                                mode_collect_in_device=args.device_auc)

    dss = []
    for _ in range(args.passes):
        ds = BoxDataset(feed, read_threads=2)
        ds.set_filelist(files)
        dss.append(ds)
    stats = run_preloaded_passes(trainer, dss)  # load N+1 ∥ train N

    for i, s in enumerate(stats):
        print(f"pass {i}: loss={s['loss']:.4f} batches={s['batches']}")
    msg = trainer.metrics.get_metric_msg("auc")
    print("streaming AUC:", round(msg["auc"], 4), "size:", int(msg["size"]))
    if ps_client is not None:
        print("rows on the PS:", ps_client.sparse_size(0))
        ps_client.stop_server()
        ps_client.close()


if __name__ == "__main__":
    main()
