"""End-to-end CTR training demo: the BoxPS day workflow on one chip.

Generates synthetic MultiSlot data, then runs the full pass cadence a
PaddleBox user knows — preload-overlapped passes, streaming AUC, two-tier
checkpointing (batch model + xbox serving view), pass-boundary recovery —
on the single-chip trainer.

    python examples/train_ctr.py [--passes 4] [--bf16]
"""

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=4)
    ap.add_argument("--bf16", action="store_true",
                    help="bfloat16 dense compute (MXU path)")
    ap.add_argument("--expand-dim", type=int, default=0,
                    help="NN-cross: train a second (expand) embedding "
                         "block per feature through the extended pull "
                         "(pull_box_extended_sparse path)")
    ap.add_argument("--push-write", default="auto",
                    choices=("auto", "scatter", "rebuild"),
                    help="slab write strategy (auto = rebuild on tpu "
                         "backends; BASELINE.md axon characterization)")
    ap.add_argument("--sparse-chunk-sync", action="store_true",
                    help="one merged table update per scan chunk "
                         "(effective sparse batch = chunk x batch; dense "
                         "adam stays exact per batch)")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    from paddlebox_tpu.config import flags
    flags.set_flag("push_write", args.push_write)

    from paddlebox_tpu.config.configs import (CheckpointConfig,
                                              SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.models import CtrDnnExpand, DeepFM
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.train.checkpoint import CheckpointManager
    from paddlebox_tpu.train.recovery import RecoverableRunner
    from paddlebox_tpu.train.trainer import BoxTrainer

    work = args.workdir or tempfile.mkdtemp(prefix="pbx_demo_")
    data_dir = os.path.join(work, "data")
    print(f"workdir: {work}")

    # --- data: 4 files of learnable synthetic CTR text (MultiSlot format)
    files, feed = write_synthetic_ctr_files(
        data_dir, num_files=4, lines_per_file=2000, num_slots=16,
        vocab_per_slot=1000, max_len=4, seed=7)
    feed = type(feed)(slots=feed.slots, batch_size=256)

    # --- model + table (DeepFM over a per-pass HBM slab)
    D = 8
    table = TableConfig(
        embedx_dim=D, pass_capacity=1 << 18,
        expand_embed_dim=args.expand_dim,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    spec = ModelSpec(num_slots=16, slot_dim=3 + D)
    model = (CtrDnnExpand(spec, expand_dim=args.expand_dim,
                          hidden=(256, 128)) if args.expand_dim
             else DeepFM(spec, hidden=(256, 128)))
    trainer = BoxTrainer(
        model,
        table, feed,
        TrainerConfig(dense_lr=1e-3,
                      compute_dtype="bfloat16" if args.bf16 else "float32",
                      sparse_chunk_sync=args.sparse_chunk_sync),
        seed=0)
    trainer.metrics.init_metric("auc", "label", "pred", mask_var="mask")

    # --- pass cadence with per-pass checkpoints (resume-able: rerun this
    #     script with --workdir to continue after a crash); see
    #     examples/train_sharded.py for the preload-overlap + multi-chip
    #     variant
    ckpt = CheckpointManager(CheckpointConfig(
        batch_model_dir=os.path.join(work, "batch_model"),
        xbox_model_dir=os.path.join(work, "xbox_model"),
        async_save=False), trainer.table)
    runner = RecoverableRunner(trainer, ckpt, day="demo")

    def datasets():
        out = []
        for _ in range(args.passes):
            ds = BoxDataset(feed, read_threads=2)
            ds.set_filelist(files)
            out.append(ds)
        return out

    done = runner.completed_passes()
    if done:
        print(f"resuming after {done} completed passes")
    stats = runner.run(datasets())  # skips completed passes itself

    for i, s in enumerate(stats):
        print(f"pass {i}: loss={s['loss']:.4f} instances={s['instances']}")
    msg = trainer.metrics.get_metric_msg("auc")
    print("streaming AUC:", {k: round(v, 4) for k, v in msg.items()
                             if k in ("auc", "size", "actual_ctr")})
    print(f"checkpoints under {work}/batch_model/demo/ "
          f"(xbox serving views under xbox_model/)")


if __name__ == "__main__":
    main()
